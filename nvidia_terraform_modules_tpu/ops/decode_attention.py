# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Pallas TPU decode attention: flash-decode over a contiguous KV cache
(optionally int8 with in-kernel dequant) and over the BLOCK/PAGED pool
with the block table folded into the kernel's DMA schedule.

The long-context serving step is KV-cache-bandwidth-bound: at [8, 3584+]
rows the bf16 cache is ~2.4 GB read per token while the (int8) weights
are 0.4 GB (``models/decode.py``). Two levers live here:

1. **int8 cache bytes** (:func:`int8_kv_decode_attention`): quantising
   the cache halves the bytes — but only if int8 is what actually
   crosses HBM. The jnp path applies the scales AFTER the contractions
   (``_cached_attention``), yet XLA still materialises converted
   operands at long S (measured: int8 KV 2185 tok/s vs bf16 2132 at
   S=3616 — parity, not the ~1.7× the byte math promises). The kernel
   removes the choice: cache tiles load as int8 into VMEM, the
   int8→bf16 convert happens right before each MXU dot, and the
   per-vector scales fold into the scores / probabilities —
   ``q·(k_q·s_k) = (q·k_q)·s_k`` and ``Σ_s p_s·(v_q·s_v)_s =
   Σ_s (p_s·s_v,s)·v_q_s`` — which are [.., S] and tiny next to the
   [.., S, D] cache.

2. **the paged-gather tax** (:func:`paged_decode_attention`): the serve
   engine's pool is ``[num_blocks, block_size, kv, D]`` physical blocks
   indexed by per-row block tables (``models/paging.py``), and the jnp
   read path materialises the logical view ``k_phys[tables] →
   [B, NT·bs, kv, D]`` every wave — HBM traffic that scales with POOL
   size, not live tokens (vLLM's PagedAttention exists to avoid exactly
   this). Here the block table is a SCALAR-PREFETCH (SMEM) input and
   the grid's S sweep walks TABLE ENTRIES: each step's K/V tile is
   DMA'd straight from its physical block (the BlockSpec index map
   reads the table), so per-wave cache traffic is the LIVE blocks.
   Dead entries — past a row's ``pos``, or a retired slot's recycled
   blocks — are aliased to reserved garbage block 0 in the index map
   (consecutive identical indices: pallas skips the re-fetch) and their
   folds skipped with ``pl.when``, the same liveness discipline as the
   splash maps in ``ops/flash_attention.py``.

Both kernels share ONE per-tile online-softmax fold (``_tile_fold``) —
the paged and contiguous variants are the same arithmetic in the same
order at equal tile sizes, differing only in where tiles are DMA'd
from, so ``paged == contiguous-on-the-gathered-view`` holds BITWISE
(``tests/test_decode_attention.py`` pins it per dtype). Against the
jnp gather path the usual flash caveat applies: the online softmax
re-orders the reduction, so parity is fp-tolerance, not bit equality.

Shape discipline (flash-decode recurrence, same VMEM model as
``ops/flash_attention.py``):

- grid (B, S-blocks) — table entries for the paged kernel; the S sweep
  is innermost so the f32 online-softmax state (m, l, acc) lives in
  VMEM scratch across it;
- the query is ONE token per batch row ([B, H, D], T=1 — the decode
  step; prefill and [B, k+1] verification keep the jnp path);
- GQA: queries reshape to [KV, rep, D] groups and contract against the
  un-repeated cache — scores are [rep, block_s] per tile;
- per-row positions mask keys at ``s > pos`` — the per-slot positions
  of the continuous-batching pool come for free; blocks entirely past
  ``pos`` are SKIPPED with ``pl.when`` (no FLOPs, no DMA use), which
  also keeps the first block always-live so the running max never sees
  a fully-dead update (the exp(-inf - -inf) NaN).

Reference analogue: none — the reference provisions serving infra and
never touches model bytes (``/root/reference/gke/README.md:50``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _per_head(xt, kv, rep, block_s):
    # [KV, bs] f32 → [KV·rep, bs]: sublane-repeat per query group
    return jnp.broadcast_to(xt[:, None, :],
                            (kv, rep, block_s)).reshape(kv * rep, block_s)


def _tile_fold(qbd, k2, v2, ks_t, vs_t, start, pos, s_total,
               m_scr, l_scr, acc_scr, *, scale, kv, rep, block_s):
    """ONE S-tile's online-softmax fold — the shared arithmetic of the
    contiguous and paged kernels. Because both call exactly this, in
    the same tile order at equal ``block_s``, the paged kernel is
    BITWISE the contiguous kernel run on the gathered logical view:
    the block-table indirection changes addresses, never bits.

    ``qbd`` is the block-diagonal query [KV·rep, KV·D] (one MXU dot
    computes every head's scores against the tile in its native
    [bs, KV·D] layout — no per-head loop, no head-major cache
    transpose); ``k2``/``v2`` the tile reshaped to [bs, KV·D] in
    compute dtype; ``ks_t``/``vs_t`` the per-vector scales as
    [KV, bs] f32 (``None`` for unquantised caches — the fold skips
    the two scale multiplies entirely); ``start`` the tile's first
    logical position.
    """
    hq = kv * rep
    d = k2.shape[-1] // kv
    s = jax.lax.dot_general(
        qbd, k2, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # [KV·rep, bs]
    if ks_t is not None:
        s = s * _per_head(ks_t, kv, rep, block_s)         # fold k scales
    s_idx = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where((s_idx <= pos) & (s_idx < s_total), s, NEG_INF)

    m_prev, l_prev = m_scr[:], l_scr[:]                   # [KV·rep, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)
    l_scr[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    if vs_t is not None:
        pv = (p * _per_head(vs_t, kv, rep, block_s)).astype(qbd.dtype)
    else:
        pv = p.astype(qbd.dtype)
    # one dot against the whole tile computes every (query-head ×
    # value-head) pair; the diagonal band — each query head with ITS
    # value head — is selected with a static one-hot reduce
    full = jax.lax.dot_general(
        pv, v2, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [KV·rep, KV·D]
    f3 = full.reshape(hq, kv, d)
    rowk = jax.lax.broadcasted_iota(jnp.int32, (hq, kv), 0) // rep
    colk = jax.lax.broadcasted_iota(jnp.int32, (hq, kv), 1)
    sel = (rowk == colk).astype(jnp.float32)[:, :, None]
    acc_scr[:] = acc_scr[:] * alpha + jnp.sum(f3 * sel, axis=1)
    m_scr[:] = m_new


def _block_diag_q(q, kv, rep, d):
    """Block-diagonal query: row ``k·rep+g`` carries head (k, g) in the
    d-band of KV head k, so ONE dot against the [bs, KV·D]-shaped cache
    tile contracts every head exactly (64 KB of h2d per step)."""
    b = q.shape[0]
    qg = q.reshape(b, kv, rep, d)
    eye = jnp.eye(kv, dtype=q.dtype)
    return (qg[:, :, :, None, :] * eye[None, :, None, :, None]).reshape(
        b, kv * rep, kv * d)


def _kernel(pos_ref, q_ref, *rest, scale, block_s, s_total, kv, rep,
            quant):
    """One (batch row, S-block) tile of the CONTIGUOUS-cache kernel:
    every KV head of the block.

    The cache tile keeps its native [block_s, KV, D] layout (a head-major
    relayout would cost a full-cache transpose per step in HBM); the
    per-head [rep, D]×[block_s, D] dots are tiny, but the op is
    cache-bandwidth-bound so MXU utilisation is irrelevant — what
    matters is that the tile is DMA'd once, at its storage width. Head
    slicing happens on the LANE axis (reshape to [block_s, KV·D],
    128-multiple column slices), which Mosaic handles natively; the
    fold itself is :func:`_tile_fold`."""
    if quant:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    si, ns = pl.program_id(1), pl.num_programs(1)

    @pl.when(si == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[0, 0, 0]
    d = k_ref.shape[-1]

    # the whole block is dead iff its first key is past this row's
    # position (pos < S always, so this also kills the ragged tail)
    @pl.when(si * block_s <= pos)
    def _live():
        qbd = q_ref[0]
        k2 = k_ref[0].astype(qbd.dtype).reshape(block_s, kv * d)
        v2 = v_ref[0].astype(qbd.dtype).reshape(block_s, kv * d)
        _tile_fold(qbd, k2, v2,
                   None if ks_ref is None else ks_ref[0],
                   None if vs_ref is None else vs_ref[0],
                   si * block_s, pos, s_total, m_scr, l_scr, acc_scr,
                   scale=scale, kv=kv, rep=rep, block_s=block_s)

    @pl.when(si == ns - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:]).astype(
            o_ref.dtype).reshape(o_ref.shape[1:])


def kv_decode_attention(q, k_cache, v_cache, pos, *, scale: float,
                        k_scale=None, v_scale=None, block_s: int = 1024,
                        interpret: bool | None = None):
    """One decode step of attention over a CONTIGUOUS cache.

    ``q [B, H, D]`` (compute dtype) attends over ``k_cache``/``v_cache``
    ``[B, S, KV, D]``; ``pos [B]`` int32 gives each row's query position
    (keys at ``s <= pos`` participate). With ``k_scale``/``v_scale``
    ``[B, S, KV]`` f32 the buffers are int8 and dequantise in-kernel
    (scale-after-dot). Returns ``[B, H, D]`` in ``q.dtype``. ``H`` must
    be a multiple of ``KV``; ``D`` a lane multiple (128) on chip.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    quant = k_scale is not None
    b, h, d = q.shape
    _, s_total, kv, _ = k_cache.shape
    rep = h // kv
    pos = jnp.asarray(pos, jnp.int32).reshape(b)
    # S must tile EXACTLY: a ragged tail block would clamp its start
    # index and silently read earlier rows under the mask. init_cache
    # rounds int8 buffers to a 256-row grain; shrink to a divisor for
    # smaller/odd buffers and refuse when none exists.
    block_s = next(
        (bs for bs in (min(block_s, s_total), 256, 128, 64, 32, 16, 8)
         if bs % 8 == 0 and s_total % bs == 0), 0)
    if not block_s:
        raise ValueError(
            f"cache rows ({s_total}) need an 8-multiple block divisor "
            f"for the decode kernel (init_cache rounds int8 to 256)")
    ns = s_total // block_s

    qbd = _block_diag_q(q, kv, rep, d)
    in_specs = [
        # per-row position as a [B, 1, 128] VMEM operand: the block's
        # trailing (1, 128) dims equal the array's, which stays legal
        # for ANY batch — including the extra leading dim jax.vmap
        # prepends when a caller batches this call (a rank-1 SMEM
        # block breaks exactly there)
        pl.BlockSpec((1, 1, 128), lambda bi, si: (bi, 0, 0)),
        pl.BlockSpec((1, kv * rep, kv * d), lambda bi, si: (bi, 0, 0)),
        pl.BlockSpec((1, block_s, kv, d), lambda bi, si: (bi, si, 0, 0)),
    ]
    args = [jnp.broadcast_to(pos[:, None, None], (b, 1, 128)), qbd,
            k_cache]
    if quant:
        in_specs.append(
            pl.BlockSpec((1, kv, block_s), lambda bi, si: (bi, 0, si)))
        args.append(jnp.asarray(k_scale, jnp.float32).swapaxes(1, 2))
    in_specs.append(
        pl.BlockSpec((1, block_s, kv, d), lambda bi, si: (bi, si, 0, 0)))
    args.append(v_cache)
    if quant:
        in_specs.append(
            pl.BlockSpec((1, kv, block_s), lambda bi, si: (bi, 0, si)))
        args.append(jnp.asarray(v_scale, jnp.float32).swapaxes(1, 2))

    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, block_s=block_s,
                          s_total=s_total, kv=kv, rep=rep, quant=quant),
        grid=(b, ns),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kv * rep, d), lambda bi, si: (bi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, kv * rep, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((kv * rep, 1), jnp.float32),  # running max m
            pltpu.VMEM((kv * rep, 1), jnp.float32),  # running normaliser l
            pltpu.VMEM((kv * rep, d), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, d)


def int8_kv_decode_attention(q, k_cache, k_scale, v_cache, v_scale, pos,
                             *, scale: float, block_s: int = 1024,
                             interpret: bool | None = None):
    """One decode step over an int8 cache — the historical entry point,
    now :func:`kv_decode_attention` with the scale sidecars required."""
    return kv_decode_attention(q, k_cache, v_cache, pos, scale=scale,
                               k_scale=k_scale, v_scale=v_scale,
                               block_s=block_s, interpret=interpret)


def _paged_kernel(tables_ref, pos_ref, q_ref, *rest, scale, bs, nt, kv,
                  rep, quant):
    """One (batch row, table entry) tile of the PAGED kernel.

    ``tables_ref``/``pos_ref`` are scalar-prefetch SMEM inputs — the
    BlockSpec index maps already used them to aim each step's K/V DMA
    at the entry's physical block, so the body only needs the liveness
    test and the shared fold. The scale sidecars arrive in the pool's
    native [bs, KV] layout and transpose IN-KERNEL to the fold's
    [KV, bs]: a tiny per-tile relayout, against which the contiguous
    wrapper's whole-cache [B, S, KV] → [B, KV, S] swap would be a
    full-pool materialisation per wave — the exact traffic this kernel
    exists to kill. Values are identical either way, so bitwise parity
    with the contiguous fold is unaffected."""
    if quant:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    bi, ti = pl.program_id(0), pl.program_id(1)

    @pl.when(ti == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[bi]
    d = k_ref.shape[-1]

    # dead entries (first key past this row's pos — recycled garbage
    # included) fold nothing; their DMA was aliased to block 0 by the
    # index map, so they also move no fresh bytes
    @pl.when(ti * bs <= pos)
    def _live():
        qbd = q_ref[0]
        k2 = k_ref[0].astype(qbd.dtype).reshape(bs, kv * d)
        v2 = v_ref[0].astype(qbd.dtype).reshape(bs, kv * d)
        ks_t = None if ks_ref is None else ks_ref[0].T
        vs_t = None if vs_ref is None else vs_ref[0].T
        _tile_fold(qbd, k2, v2, ks_t, vs_t, ti * bs, pos, nt * bs,
                   m_scr, l_scr, acc_scr, scale=scale, kv=kv, rep=rep,
                   block_s=bs)

    @pl.when(ti == nt - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:]).astype(
            o_ref.dtype).reshape(o_ref.shape[1:])


def paged_decode_attention(q, k_pool, v_pool, tables, pos, *,
                           scale: float, k_scale=None, v_scale=None,
                           interpret: bool | None = None):
    """One decode step of attention THROUGH the block tables — no
    logical-view gather.

    ``q [B, H, D]`` attends over the physical pool ``k_pool``/``v_pool``
    ``[num_blocks, block_size, KV, D]`` via ``tables [B, NT]`` int32
    (each row's logical block i lives at physical block
    ``tables[b, i]``) and per-row ``pos [B]`` int32 (keys at logical
    ``s <= pos`` participate — which also fences recycled-block
    garbage and frozen retired slots, exactly as the gather path's
    position mask does). Int8 pools pass ``k_scale``/``v_scale``
    ``[num_blocks, block_size, KV]`` f32 sidecars riding the same
    tables, dequantised in-kernel (scale-after-dot). Returns
    ``[B, H, D]`` in ``q.dtype``.

    The table and positions are SCALAR-PREFETCH inputs: pallas reads
    them in SMEM before the grid runs, so each (row, entry) step's K/V
    BlockSpec index map can aim the tile DMA at ``tables[b, i]``
    directly — per-step HBM traffic is the row's LIVE blocks, not the
    ``NT·bs``-row logical view the jnp path materialises. Dead entries
    alias to reserved garbage block 0 (consecutive repeats of one
    index: pallas skips the re-fetch) and skip their folds.

    On chip ``D`` must be a lane multiple (128) and ``block_size`` a
    sublane multiple (8); interpret mode (the CPU test path) takes any
    shape. Equal tile contents in equal order make this BITWISE
    :func:`kv_decode_attention` over the gathered view at
    ``block_s=block_size`` — pinned per dtype in
    ``tests/test_decode_attention.py``.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    quant = k_scale is not None
    b, h, d = q.shape
    _nb, bs, kv, _ = k_pool.shape
    nt = tables.shape[1]
    rep = h // kv
    if h % kv:
        raise ValueError(f"q heads ({h}) must be a multiple of the "
                         f"pool's kv heads ({kv})")
    if not interpret and (d % 128 or bs % 8):
        raise ValueError(
            f"paged decode kernel on chip needs head_dim % 128 == 0 "
            f"(got {d}) and block_size % 8 == 0 (got {bs}) — use a "
            f"lane-aligned head_dim and kv_block, or the gather path")
    tables = jnp.asarray(tables, jnp.int32)
    pos = jnp.asarray(pos, jnp.int32).reshape(b)
    qbd = _block_diag_q(q, kv, rep, d)

    def pool_map(bi, ti, tr, pr):
        # live → the entry's physical block; dead → garbage block 0
        # (repeated index: no re-fetch). The liveness test MUST equal
        # the kernel's pl.when, or a folded tile could hold the wrong
        # block's bytes.
        return (jnp.where(ti * bs <= pr[bi], tr[bi, ti], 0), 0, 0, 0)

    def scale_map(bi, ti, tr, pr):
        return (jnp.where(ti * bs <= pr[bi], tr[bi, ti], 0), 0, 0)

    def row_map(bi, ti, tr, pr):
        return (bi, 0, 0)

    in_specs = [pl.BlockSpec((1, kv * rep, kv * d), row_map),
                pl.BlockSpec((1, bs, kv, d), pool_map)]
    args = [qbd, k_pool]
    if quant:
        in_specs.append(pl.BlockSpec((1, bs, kv), scale_map))
        args.append(jnp.asarray(k_scale, jnp.float32))
    in_specs.append(pl.BlockSpec((1, bs, kv, d), pool_map))
    args.append(v_pool)
    if quant:
        in_specs.append(pl.BlockSpec((1, bs, kv), scale_map))
        args.append(jnp.asarray(v_scale, jnp.float32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kv * rep, d), row_map),
        scratch_shapes=[
            pltpu.VMEM((kv * rep, 1), jnp.float32),  # running max m
            pltpu.VMEM((kv * rep, 1), jnp.float32),  # running normaliser l
            pltpu.VMEM((kv * rep, d), jnp.float32),  # output accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, bs=bs, nt=nt,
                          kv=kv, rep=rep, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv * rep, d), q.dtype),
        interpret=interpret,
    )(tables, pos, *args)
    return out.reshape(b, h, d)
