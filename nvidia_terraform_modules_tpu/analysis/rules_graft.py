# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The graft rule pack: runtime-convention checks over Python AST.

Each rule is the static form of a convention the runtime already
enforces by review: string-seeded RNG (PYTHONHASHSEED-immune replay),
no host sync inside jitted wave loops, the injected telemetry clock,
classified-never-silent error handling (the ``HandoffCorruptError`` /
``HostSpillCorruptError`` pattern), lock-ordered thread-shared state,
and no reuse of buffers donated to a jit. All checks are best-effort
syntactic analyses — they resolve import aliases but do not infer
types — tuned so the clean idiom never fires and the violation idiom
always does.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .graftlint import rule
from .pysrc import PyContext, dotted, self_attr, walk_scope


@rule("graft-load", severity="error", family="core",
      summary="every scanned file must parse")
def check_load(ctx: PyContext):
    # force every tree so parse failures are collected, then surface
    # them — a broken file must fail the run, not silently drop its
    # findings
    for _ in ctx.trees():
        pass
    return list(ctx.load_errors)


# ------------------------------------------------------------------- rng

# draw methods whose module-level form uses the interpreter-global RNG
_GLOBAL_DRAWS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "getrandbits", "randbytes",
    "rand", "randn", "normal", "standard_normal", "permutation",
}
_RNG_FACTORIES = {
    "random.Random", "numpy.random.default_rng", "numpy.random.RandomState",
}


def _seed_arg(call: ast.Call) -> Optional[ast.AST]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg in ("seed", "x"):
            return kw.value
    return None


@rule("graft-unseeded-rng", severity="error", family="rng",
      summary="RNG draws must follow the string-seeded convention")
def check_unseeded_rng(ctx: PyContext):
    for fname, tree in ctx.trees():
        for node in ctx.nodes(fname):
            if not isinstance(node, ast.Call):
                continue
            r = ctx.resolve(fname, node.func)
            if r is None:
                continue
            where = f"{fname}:{node.lineno}"
            if r in _RNG_FACTORIES:
                seed = _seed_arg(node)
                if seed is None:
                    yield (where,
                           f"seedless {r}() draws from process entropy — "
                           f"replay breaks; seed from a string: "
                           f'random.Random(f"{{salt}}-{{seed}}")')
                elif isinstance(seed, ast.Constant) and \
                        isinstance(seed.value, (int, float)) and \
                        not isinstance(seed.value, bool):
                    yield (where,
                           f"integer-literal seed {r}({seed.value!r}) — "
                           f"literal seeds collide across components; "
                           f"derive the seed from a string salt "
                           f"(string-seeded convention)")
                elif isinstance(seed, ast.Call) and \
                        ctx.resolve(fname, seed.func) == "hash":
                    yield (where,
                           f"{r}(hash(...)) varies with PYTHONHASHSEED — "
                           f"derive the seed with a keyed digest "
                           f"(blake2b) per the string-seeded convention")
            elif r in ("random.seed", "numpy.random.seed"):
                yield (where,
                       f"{r}() reseeds the shared global RNG — action at "
                       f"a distance across every module; use a local "
                       f"string-seeded Random instead")
            elif (r.startswith("random.")
                  and r.partition(".")[2] in _GLOBAL_DRAWS) or \
                 (r.startswith("numpy.random.")
                  and r.rpartition(".")[2] in _GLOBAL_DRAWS):
                yield (where,
                       f"{r}() draws from the shared global RNG — any "
                       f"import-order or call-order change shifts the "
                       f"stream; draw from a local string-seeded Random")


# -------------------------------------------------- traced-scope helpers

# traced higher-order primitives → positional index of the body callable
_TRACED_CALLS = {
    "jax.lax.scan": (0,),
    "jax.lax.fori_loop": (2,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.cond": (1, 2),
    "jax.lax.map": (0,),
}

_JIT_WRAPPERS = ("jax.jit", "jax.pmap")


def _is_jit_expr(ctx: PyContext, fname: str, node: ast.AST) -> bool:
    """True for ``jax.jit``, ``jax.jit(...)``, and
    ``functools.partial(jax.jit, ...)`` decorator/value expressions."""
    if ctx.resolve(fname, node) in _JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        rf = ctx.resolve(fname, node.func)
        if rf in _JIT_WRAPPERS:
            return True
        if rf == "functools.partial" and node.args and \
                ctx.resolve(fname, node.args[0]) in _JIT_WRAPPERS:
            return True
    return False


def _traced_scopes(ctx: PyContext, fname: str,
                   tree: ast.Module) -> list[ast.AST]:
    """Function/lambda nodes whose bodies run under trace: jit/pmap
    decorated defs, plus the body callables handed to scan/fori/while/
    cond (by literal lambda or by local def name). Memoized per file —
    both the sync and wallclock rules need it."""
    cached = ctx.memo.get(("traced", fname))
    if cached is not None:
        return cached
    defs: dict[str, ast.AST] = {}
    for n in ctx.nodes(fname):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(n.name, n)
    marked: list[ast.AST] = []
    for n in ctx.nodes(fname):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(ctx, fname, d) for d in n.decorator_list):
                marked.append(n)
        elif isinstance(n, ast.Call):
            positions = _TRACED_CALLS.get(ctx.resolve(fname, n.func) or "")
            for p in positions or ():
                if p < len(n.args):
                    a = n.args[p]
                    if isinstance(a, ast.Lambda):
                        marked.append(a)
                    elif isinstance(a, ast.Name) and a.id in defs:
                        marked.append(defs[a.id])
    ctx.memo[("traced", fname)] = marked
    return marked


def _jitted_names(ctx: PyContext, fname: str, tree: ast.Module) -> set:
    """Local names bound to jitted callables: jit-decorated defs and
    ``name = jax.jit(...)`` / ``partial(jax.jit, ...)`` assignments."""
    cached = ctx.memo.get(("jitted", fname))
    if cached is not None:
        return cached
    names = set()
    for n in ctx.nodes(fname):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(ctx, fname, d) for d in n.decorator_list):
                names.add(n.name)
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and _is_jit_expr(ctx, fname, n.value):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    ctx.memo[("jitted", fname)] = names
    return names


# ------------------------------------------------------------- host sync

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_SYNC_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}


def _sync_calls(ctx: PyContext, fname: str, nodes: Iterator[ast.AST],
                casts: bool) -> Iterator[tuple[ast.Call, str]]:
    for n in nodes:
        if not isinstance(n, ast.Call):
            continue
        if isinstance(n.func, ast.Attribute) and n.func.attr in _SYNC_ATTRS:
            yield n, f".{n.func.attr}()"
            continue
        r = ctx.resolve(fname, n.func)
        if r in _SYNC_CALLS:
            yield n, f"{r}()"
        elif casts and r in ("float", "bool") and len(n.args) == 1 and \
                not isinstance(n.args[0], ast.Constant):
            yield n, f"{r}()"


@rule("graft-host-sync-in-loop", severity="error", family="sync",
      summary="no device→host sync inside jitted/wave loop bodies")
def check_host_sync(ctx: PyContext):
    for fname, tree in ctx.trees():
        seen = set()
        # traced bodies: any sync there either breaks tracing or bakes a
        # trace-time constant — float()/bool() casts of tracers included
        for scope in _traced_scopes(ctx, fname, tree):
            for call, what in _sync_calls(ctx, fname, ast.walk(scope),
                                          casts=True):
                key = (call.lineno, call.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield (f"{fname}:{call.lineno}",
                       f"{what} inside a traced (jit/scan/fori) body — "
                       f"hoist the sync to host code outside the trace")
        # wave loops: host for/while loops that drive a jitted step —
        # a per-iteration sync serialises device against host every wave
        jitted = _jitted_names(ctx, fname, tree)
        if not jitted:
            continue
        for loop in ctx.nodes(fname):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            body = walk_scope(loop)
            drives = any(isinstance(n, ast.Call)
                         and isinstance(n.func, ast.Name)
                         and n.func.id in jitted for n in body)
            if not drives:
                continue
            for call, what in _sync_calls(ctx, fname, walk_scope(loop),
                                          casts=False):
                key = (call.lineno, call.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield (f"{fname}:{call.lineno}",
                       f"{what} inside a wave loop driving a jitted step "
                       f"— forces a device→host sync every iteration; "
                       f"aggregate on device and sync once after the loop")


# ------------------------------------------------------------- wallclock

# epoch clocks: nondeterministic AND non-monotonic — never belong in
# runtime logic outside the allowlist
_EPOCH_CLOCKS = {
    "time.time", "time.time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}
# interval clocks: still nondeterministic, but deadline arithmetic in
# the threaded serving runtime is genuinely a real-time domain — those
# modules get a wider allowlist
_INTERVAL_CLOCKS = {
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
}
_WALLCLOCK = _EPOCH_CLOCKS | _INTERVAL_CLOCKS

# path fragments where wallclock reads are the point: the telemetry
# clock itself, retry backoff jitter, profiling, multihost barriers,
# the simulator/CLI layers, and this analysis package's own watchdog
_WALLCLOCK_ALLOW = (
    "telemetry/", "tfsim/", "smoketest/", "analysis/",
    "utils/timing.py", "utils/retry.py", "utils/profiling.py",
    "parallel/multihost.py",
)
# the threaded serving runtime: poll deadlines, heartbeat intervals and
# wave timers measure REAL elapsed time by design — interval clocks are
# fine there, epoch clocks still are not
_INTERVAL_ALLOW = _WALLCLOCK_ALLOW + (
    "models/fleet.py", "models/serving.py", "models/hostkv.py",
    "models/resilience.py", "models/checkpoint.py",
    "models/transport.py",
)


@rule("graft-wallclock-nondeterminism", severity="warning",
      family="determinism",
      summary="wallclock reads belong behind the injected clock")
def check_wallclock(ctx: PyContext):
    for fname, tree in ctx.trees():
        traced = _traced_scopes(ctx, fname, tree)
        in_trace = {id(n) for scope in traced for n in ast.walk(scope)}
        # default-arg wallclock calls are a bug in EVERY file: evaluated
        # once at import, frozen forever
        in_default = set()
        for n in ctx.nodes(fname):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                for d in (list(n.args.defaults)
                          + [k for k in n.args.kw_defaults if k]):
                    in_default.update(id(x) for x in ast.walk(d))
        for n in ctx.nodes(fname):
            if not isinstance(n, ast.Call) or \
                    ctx.resolve(fname, n.func) not in _WALLCLOCK:
                continue
            r = ctx.resolve(fname, n.func)
            allow = _INTERVAL_ALLOW if r in _INTERVAL_CLOCKS \
                else _WALLCLOCK_ALLOW
            allowed = any(frag in fname for frag in allow)
            where = f"{fname}:{n.lineno}"
            if id(n) in in_default:
                yield (where,
                       f"{r}() in default-argument position is evaluated "
                       f"once at import and frozen — default to None and "
                       f"read the clock inside the body")
            elif id(n) in in_trace:
                yield (where,
                       f"{r}() inside a traced body becomes a trace-time "
                       f"constant — every retrace bakes a new value; "
                       f"pass time in as an argument")
            elif not allowed:
                yield (where,
                       f"{r}() outside the telemetry-clock/backoff "
                       f"allowlist — inject the clock (telemetry "
                       f"`clock=`) or take `now` as a parameter so "
                       f"replay and tests stay deterministic")


# ---------------------------------------------------------- silent except

_BROAD = {"Exception", "BaseException"}


def _broad_types(ctx: PyContext, fname: str,
                 h: ast.ExceptHandler) -> Optional[str]:
    """The broad type name a handler catches, None for specific types."""
    if h.type is None:
        return "bare"
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        r = ctx.resolve(fname, t)
        if r in _BROAD:
            return r
    return None


@rule("graft-silent-except", severity="warning", family="errors",
      summary="broad except must classify, not swallow")
def check_silent_except(ctx: PyContext):
    for fname, tree in ctx.trees():
        lines = ctx.text(fname).splitlines()
        for node in ctx.nodes(fname):
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                broad = _broad_types(ctx, fname, h)
                if broad is None:
                    continue
                # an explicit ruff blind-except exemption on the handler
                # line is an already-reviewed broad catch — respect it
                # rather than demanding a second suppression marker
                if 0 < h.lineno <= len(lines) and \
                        "noqa: BLE001" in lines[h.lineno - 1]:
                    continue
                reraises = any(isinstance(n, ast.Raise)
                               for n in walk_scope(h))
                if reraises:
                    continue
                where = f"{fname}:{h.lineno}"
                if broad == "bare":
                    # a bare handler has no bound name to inspect: if it
                    # does not re-raise it swallowed KeyboardInterrupt
                    yield (where,
                           "bare except swallows KeyboardInterrupt/"
                           "SystemExit along with real errors — catch a "
                           "classified type (HandoffCorruptError "
                           "pattern) or re-raise")
                    continue
                used = h.name is not None and any(
                    isinstance(n, ast.Name) and n.id == h.name
                    and isinstance(n.ctx, ast.Load)
                    for n in walk_scope(h))
                if not used:
                    yield (where,
                           f"except {broad} swallows the error without "
                           f"classifying it — map it to a typed error "
                           f"(HostSpillCorruptError pattern), log it, "
                           f"or re-raise")


# -------------------------------------------------- unlocked shared state

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                   "threading.Condition"}

_MUTATORS = {"append", "appendleft", "add", "remove", "discard", "pop",
             "popleft", "clear", "update", "insert", "extend",
             "setdefault", "popitem"}


def _method_writes(method: ast.AST, lock_attrs: set,
                   ) -> Iterator[tuple[str, int, bool]]:
    """(attr, line, held) for every write to ``self.<attr>`` in a
    method: assignments, augmented assigns, item stores/deletes, and
    mutating container-method calls."""

    def visit(node: ast.AST, held: bool) -> Iterator[tuple[str, int, bool]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            h = held
            if isinstance(child, ast.With):
                if any(self_attr(item.context_expr) in lock_attrs
                       for item in child.items):
                    h = True
            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = child.targets if isinstance(child, ast.Assign) \
                    else [child.target]
                for t in targets:
                    a = self_attr(t)
                    if a is not None and a not in lock_attrs:
                        yield a, child.lineno, held
                    elif isinstance(t, ast.Subscript):
                        a = self_attr(t.value)
                        if a is not None:
                            yield a, child.lineno, held
            elif isinstance(child, ast.Delete):
                for t in child.targets:
                    if isinstance(t, ast.Subscript):
                        a = self_attr(t.value)
                        if a is not None:
                            yield a, child.lineno, held
            elif isinstance(child, ast.Call) and \
                    isinstance(child.func, ast.Attribute) and \
                    child.func.attr in _MUTATORS:
                a = self_attr(child.func.value)
                if a is not None:
                    yield a, child.lineno, held
            yield from visit(child, h)

    yield from visit(method, False)


@rule("graft-unlocked-shared-state", severity="error", family="locking",
      summary="attributes locked anywhere must be locked everywhere")
def check_unlocked_shared_state(ctx: PyContext):
    for fname, tree in ctx.trees():
        for cls in ctx.nodes(fname):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            lock_attrs = set()
            for m in methods:
                for n in walk_scope(m):
                    if isinstance(n, ast.Assign) and \
                            isinstance(n.value, ast.Call) and \
                            ctx.resolve(fname, n.value.func) \
                            in _LOCK_FACTORIES:
                        for t in n.targets:
                            a = self_attr(t)
                            if a is not None:
                                lock_attrs.add(a)
            if not lock_attrs:
                continue
            writes = []
            for m in methods:
                for attr, line, held in _method_writes(m, lock_attrs):
                    writes.append((m.name, attr, line, held))
            protected = {attr for mname, attr, _, held in writes
                         if held and mname != "__init__"}
            for mname, attr, line, held in writes:
                if held or attr not in protected:
                    continue
                if mname == "__init__" or mname.endswith("_locked"):
                    # __init__ publishes no shared state yet; *_locked
                    # names the convention "caller already holds it"
                    continue
                yield (f"{fname}:{line}",
                       f"self.{attr} is written under the lock elsewhere "
                       f"in {cls.name} but written here without it — "
                       f"this write races; hold the lock (or name the "
                       f"method *_locked if the caller holds it)")


# ----------------------------------------------------------- donated reuse

def _donators(ctx: PyContext, fname: str,
              tree: ast.Module) -> dict[str, tuple[int, ...]]:
    """Local callable name → donated positional-argument indices, from
    jit decorations and assignments carrying ``donate_argnums``."""

    def positions(call: ast.Call) -> tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = tuple(e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int))
                    if out:
                        return out
        return ()

    out: dict[str, tuple[int, ...]] = {}
    for n in ctx.nodes(fname):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in n.decorator_list:
                if isinstance(d, ast.Call) and \
                        _is_jit_expr(ctx, fname, d):
                    pos = positions(d)
                    if pos:
                        out[n.name] = pos
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and _is_jit_expr(ctx, fname, n.value):
            pos = positions(n.value)
            if pos:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = pos
    return out


def _stmt_stores(stmt: ast.AST) -> set:
    """Every dotted name stored ANYWHERE in a statement (including
    nested bodies) — the conservative revive set."""
    stores = set()
    for n in walk_scope(stmt):
        if isinstance(n, (ast.Name, ast.Attribute)) and \
                isinstance(n.ctx, (ast.Store, ast.Del)):
            d = dotted(n)
            if d is not None:
                stores.add(d)
    return stores


@rule("graft-donated-reuse", severity="error", family="memory",
      summary="a buffer donated to a jit is dead after the call")
def check_donated_reuse(ctx: PyContext):
    for fname, tree in ctx.trees():
        donators = _donators(ctx, fname, tree)
        if not donators:
            continue
        scopes = [tree] + [n for n in ctx.nodes(fname)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from _scan_donations(fname, scope.body, donators, {})


def _stmt_nodes(stmt: ast.AST) -> Iterator[ast.AST]:
    """The statement's own expression nodes, excluding nested statement
    bodies (those are scanned recursively with their own dead-set)."""
    skip = set()
    for attr in ("body", "orelse", "finalbody", "handlers"):
        for sub in getattr(stmt, attr, []) or []:
            skip.add(id(sub))
    stack = [c for c in ast.iter_child_nodes(stmt) if id(c) not in skip]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if id(c) not in skip)


def _scan_donations(fname: str, body: list, donators: dict,
                    dead: dict) -> Iterator[tuple[str, str]]:
    """Linear scan of one statement list. ``dead`` maps a dotted buffer
    name to the (line, callee) that donated it; loads of dead names are
    findings, stores revive. Nested bodies are scanned with a copy of
    the dead-set; any store anywhere in a compound statement revives
    conservatively (a maybe-reassigned buffer is not reported)."""
    for stmt in body:
        nodes = list(_stmt_nodes(stmt))
        # loads of already-dead buffers (checked against the dead-set
        # BEFORE this statement's own donations take effect)
        for n in nodes:
            if isinstance(n, (ast.Name, ast.Attribute)) and \
                    isinstance(n.ctx, ast.Load):
                d = dotted(n)
                if d in dead:
                    line, callee = dead.pop(d)  # report once per buffer
                    yield (f"{fname}:{n.lineno}",
                           f"{d} was donated to {callee}() at line "
                           f"{line} — its device buffer is freed by "
                           f"donate_argnums; rebind it from the call's "
                           f"result before reuse")
        # this statement's donations
        donated: dict[str, tuple[int, str]] = {}
        for n in nodes:
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in donators:
                for p in donators[n.func.id]:
                    if p < len(n.args):
                        d = dotted(n.args[p])
                        if d is not None:
                            donated[d] = (n.lineno, n.func.id)
        stores = _stmt_stores(stmt)
        for d, site in donated.items():
            if d not in stores:
                dead[d] = site
        for d in stores:
            dead.pop(d, None)
        # nested statement lists: loops re-check their own body with the
        # post-body dead-set once more, so a buffer donated on iteration
        # N and read at the top of iteration N+1 is caught
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            inner = dict(dead)
            sink = list(_scan_donations(fname, stmt.body, donators, inner))
            yield from sink
            if not sink:
                # second pass models the back-edge: only when the first
                # pass was clean (avoid duplicate straight-line reports)
                yield from _scan_donations(fname, stmt.body, donators,
                                           dict(inner))
            yield from _scan_donations(fname, stmt.orelse, donators,
                                       dict(dead))
        elif not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
            # nested defs are separate scopes, scanned on their own
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    yield from _scan_donations(fname, sub, donators,
                                               dict(dead))
            for h in getattr(stmt, "handlers", []) or []:
                yield from _scan_donations(fname, h.body, donators,
                                           dict(dead))


# ---------------------------------------------------------- unbounded recv

# the serving runtime: the files where a blocking receive or join can
# wedge a router, a replica, or the caller's fleet join — every wait
# there must be bounded (the transport seam's FrameChannel discipline)
_RECV_SCOPE = (
    "models/fleet.py", "models/serving.py", "models/transport.py",
    "models/hostkv.py", "models/resilience.py",
)
# receive-shaped methods that block forever without a timeout
_RECV_METHODS = {"get", "recv", "recv_bytes", "accept"}
# the bounded-receive idiom: a function that polls (or sets a socket
# timeout on) the connection before reading has bounded its own wait —
# FrameChannel.recv's poll-then-recv_bytes shape
_RECV_GUARDS = {"poll", "settimeout"}


@rule("graft-unbounded-recv", severity="error", family="liveness",
      summary="serving-runtime recv/join must carry a timeout")
def check_unbounded_recv(ctx: PyContext):
    """A socket/pipe/queue receive or a thread/process join without a
    timeout inside the serving runtime is a latent hang: a dead peer
    (a SIGKILLed replica process, a wedged worker) then blocks the
    router forever instead of raising a classified, retryable error.
    Flags zero-argument ``.join()`` and timeout-less
    ``.get()``/``.recv()``/``.recv_bytes()``/``.accept()`` in the
    serving-runtime files, except receives in a function that bounds
    its own wait with ``.poll(...)``/``.settimeout(...)`` first."""
    for fname, _tree in ctx.trees():
        if not any(frag in fname for frag in _RECV_SCOPE):
            continue
        for fn in ctx.nodes(fname):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            guarded = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _RECV_GUARDS
                for n in walk_scope(fn))
            for n in walk_scope(fn):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)):
                    continue
                attr = n.func.attr
                where = f"{fname}:{n.lineno}"
                if attr == "join" and not n.args and not n.keywords:
                    yield (where,
                           "unbounded .join() in the serving runtime — "
                           "a wedged worker hangs the caller forever; "
                           "join with a timeout and classify the "
                           "stragglers (fleet joins raise "
                           "FleetWorkerHung)")
                elif attr in _RECV_METHODS and not n.args \
                        and not any(k.arg == "timeout"
                                    for k in n.keywords) \
                        and not guarded:
                    yield (where,
                           f"unbounded .{attr}() in the serving "
                           f"runtime — a dead peer blocks this wait "
                           f"forever; pass a timeout (or poll the "
                           f"connection first) and raise the "
                           f"classified transport error on expiry")


# ---------------------------------------------------- spawn retry/classify

# process-spawning constructors: a child whose bring-up can fail
# TRANSIENTLY (fork/exec pressure, an interpreter that dies before the
# handshake) and must therefore never be a bare call in the serving
# runtime
_SPAWN_CALLS = {"Process", "Popen"}
# the classified-bring-up idiom: the spawn — or an ENCLOSING function;
# transport's ``_spawn`` wraps the nested ``bring_up`` closure — runs
# under ``utils/retry.retry_call``, whose policy bounds the attempts
# and whose exhaustion raises the classified terminal error the fleet
# converts to a DEAD target that redrives
_SPAWN_GUARDS = {"retry_call"}


def _function_chains(tree):
    """Every function def paired with its enclosing-function chain
    (outermost first, nested defs included) — the scope lineage a
    guard search walks, so a closure handed to a retry wrapper one
    level up still counts as guarded."""
    out: list[tuple[ast.AST, list]] = []

    def visit(node, chain):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, chain))
                visit(child, chain + [child])
            else:
                visit(child, chain)

    visit(tree, [])
    return out


def _calls_guard(scope) -> bool:
    for n in walk_scope(scope):
        if isinstance(n, ast.Call):
            callee = n.func
            if isinstance(callee, ast.Name) and \
                    callee.id in _SPAWN_GUARDS:
                return True
            if isinstance(callee, ast.Attribute) and \
                    callee.attr in _SPAWN_GUARDS:
                return True
    return False


@rule("graft-spawn-no-retry-classify", severity="error",
      family="liveness",
      summary="serving-runtime process spawns must retry then classify")
def check_spawn_no_retry_classify(ctx: PyContext):
    """A ``Process``/``Popen`` spawn in the serving runtime without a
    classified retry path is a latent hang-or-crash: a transient
    bring-up failure (fork pressure, a child that dies before its
    handshake) either wedges the caller or escapes as an unclassified
    exception, instead of retrying under a bounded policy and — on
    exhaustion — raising the terminal classification the fleet turns
    into a DEAD target whose requests redrive. Flags spawn-shaped
    calls in the serving-runtime files whose enclosing function chain
    never calls ``retry_call`` (the guard search walks ENCLOSING
    functions: a nested ``bring_up`` closure handed to ``retry_call``
    one level up is the blessed idiom)."""
    for fname, tree in ctx.trees():
        if not any(frag in fname for frag in _RECV_SCOPE):
            continue
        for fn, chain in _function_chains(tree):
            if any(_calls_guard(s) for s in (*chain, fn)):
                continue
            for n in walk_scope(fn):
                if not isinstance(n, ast.Call):
                    continue
                callee = n.func
                name = callee.attr if isinstance(callee, ast.Attribute) \
                    else callee.id if isinstance(callee, ast.Name) \
                    else None
                if name in _SPAWN_CALLS:
                    yield (f"{fname}:{n.lineno}",
                           f"bare {name}() spawn in the serving "
                           f"runtime — a transient bring-up failure "
                           f"crashes or wedges the caller; wrap the "
                           f"spawn in utils/retry.retry_call with a "
                           f"bounded policy and classify exhaustion "
                           f"as the terminal (DEAD, redrive) error")


# ---------------------------------------------- durable write atomicity

# where durable serving-runtime state lives: model/engine persistence
# (checkpoints, the AOT compile cache, the disk prefix tier, elastic
# supervisor state) and the shared utils. tfsim's state files have
# their own locking/backup discipline and are out of scope here.
_DURABLE_SCOPE = ("models/", "utils/")
# the atomic-durability idiom's signals: a scope that renames a tmp
# file into place (os.replace/os.rename) — or at least fsyncs what it
# wrote — has done the crash-safety work this rule checks for
_ATOMIC_CALLS = {"os.replace", "os.rename", "os.fsync"}
# never-atomic pathlib one-shots (no handle to fsync, no tmp+rename)
_PATH_WRITES = {"write_bytes", "write_text"}


def _write_mode(ctx: PyContext, fname: str, call: ast.Call):
    """The constant mode string of an ``open``/``io.open`` call when it
    WRITES (contains w/x/a), else None. Dynamic modes are skipped —
    best-effort, like every rule here."""
    if ctx.resolve(fname, call.func) not in ("open", "io.open"):
        return None
    mode = call.args[1] if len(call.args) >= 2 else next(
        (kw.value for kw in call.keywords if kw.arg == "mode"), None)
    if mode is None:
        return None                      # default "r": a read
    if not (isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)):
        return None
    return mode.value if set(mode.value) & set("wxa") else None


def _scope_is_atomic(ctx: PyContext, fname: str, scope: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call)
        and ctx.resolve(fname, n.func) in _ATOMIC_CALLS
        for n in walk_scope(scope))


def _tmp_marked(node: ast.AST) -> bool:
    """True when the written path's expression names the TMP half of
    the atomic idiom (``tmp = f"{path}.tmp.{pid}"``; the os.replace
    that publishes it may live in an outer scope or a helper)."""
    return "tmp" in ast.unparse(node).lower()


@rule("graft-durable-write-no-atomic", severity="error",
      family="durability",
      summary="durable serving-runtime writes must be tmp+replace/fsync")
def check_durable_write_no_atomic(ctx: PyContext):
    """A serving-runtime file written WITHOUT the atomic durability
    idiom is a torn-state bug waiting for a SIGKILL: a reader after
    the crash sees a half-written frame where the contract (checkpoint
    shards, the GAC1 AOT cache, the PCD1 disk prefix tier, supervisor
    state) promises either the old record or the new one. Flags
    write-mode ``open()`` calls (and the never-atomic
    ``Path.write_bytes``/``write_text``) in the durable-scope files
    whose function scope neither renames a tmp file into place
    (``os.replace``/``os.rename``) nor fsyncs, and whose target path
    is not itself the tmp half of the idiom. The blessed shape:
    write ``f"{path}.tmp.{pid}"``, flush + ``os.fsync``, then
    ``os.replace(tmp, path)``."""
    for fname, tree in ctx.trees():
        if not any(frag in fname for frag in _DURABLE_SCOPE):
            continue
        scopes = [tree] + [n for n in ctx.nodes(fname)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            if _scope_is_atomic(ctx, fname, scope):
                continue
            for n in walk_scope(scope):
                if not isinstance(n, ast.Call):
                    continue
                where = f"{fname}:{n.lineno}"
                mode = _write_mode(ctx, fname, n)
                if mode is not None and n.args \
                        and not _tmp_marked(n.args[0]):
                    yield (where,
                           f"open(..., {mode!r}) writes durable "
                           f"serving-runtime state in place — a crash "
                           f"mid-write leaves a torn file where "
                           f"readers expect old-or-new; write to a "
                           f"tmp name, flush + os.fsync, then "
                           f"os.replace(tmp, path) (the aotcache/"
                           f"DiskChainStore idiom)")
                elif isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _PATH_WRITES \
                        and not _tmp_marked(n.func.value):
                    yield (where,
                           f".{n.func.attr}() writes durable state in "
                           f"one unsynced shot — no handle to fsync, "
                           f"no tmp+rename; use the atomic idiom "
                           f"(tmp file + os.fsync + os.replace)")
