# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Collective micro-probes: correctness + achieved ICI bandwidth.

These are the executable replacement for the reference's manual "is the fabric
up" checks (node-to-node SG rules at ``/root/reference/eks/main.tf:28-49`` plus
README runbooks). Each probe returns (ok, seconds, bytes_moved) so callers can
derive achieved bandwidth. All are built on ``shard_map`` so they compile to
bare XLA collectives over the mesh — no NCCL analogue, the compiler owns the
schedule.

Multi-host discipline: probe inputs are generated inside the sharded
computation and correctness is judged device-side — each probe reduces its own
error metric over every mesh axis and returns a fully-replicated scalar, the
one kind of global array any process may fetch. The same probes therefore run
unchanged on a single chip, a virtual CPU mesh, or a multi-host slice under
``jax.distributed``.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import pspec_axes, shard_map  # noqa: F401 — re-exported
from ..utils.timing import delta_time


def _axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def _replicate(err, mesh: Mesh):
    """Max-reduce an error scalar over every mesh axis → replicated output."""
    return jax.lax.pmax(err, tuple(mesh.axis_names))


def _run(mesh: Mesh, verify_kernel, timed_step, timed_spec,
         moved_bytes: float, n_dev: int, tol: float = 1e-5):
    """Judge correctness and time the collective as two separate programs.

    - ``verify_kernel`` returns a replicated error scalar (fetchable from any
      process) — correctness, fused with whatever math it needs.
    - ``timed_step(carry) -> carry`` is one data-dependent hop of the bare
      collective; a ``lax.scan`` chains it and the two-point ``delta_time``
      (1 vs 9 iterations) cancels the fixed dispatch + host-sync latency —
      which would otherwise swamp a sub-ms collective on a tunnelled
      backend. Sync reads one element of the LOCAL shard per process, so
      the measurement is multi-host safe.
    """
    verify = jax.jit(
        functools.partial(shard_map, mesh=mesh, in_specs=(), out_specs=P())(
            verify_kernel)
    )
    err = float(jax.device_get(verify()))

    def make_chain(length):
        def kernel():
            def step(carry, _):
                return timed_step(carry), None

            out, _ = jax.lax.scan(step, timed_step(None), None, length=length)
            return out

        return jax.jit(
            functools.partial(
                shard_map, mesh=mesh, in_specs=(), out_specs=timed_spec)(
                kernel)
        )

    secs = delta_time(make_chain, iters_lo=1, iters_hi=9)
    return {
        "ok": err <= tol,
        "max_error": err,
        "seconds": secs,
        "bytes": moved_bytes,
        "participants": n_dev,
    }


def psum_probe(mesh: Mesh, axis: str = "dp", n_elems: int = 1 << 20) -> dict[str, Any]:
    """All-reduce over ``axis`` — the north-star invariant.

    Each shard contributes ``axis_index + 1`` (NOT a replicated constant:
    XLA's replication analysis rewrites an all-reduce of provably-identical
    operands into local arithmetic, which would verify — and time — nothing),
    so the result must equal 1 + 2 + … + n everywhere.
    """
    n_dev = _axis_size(mesh, axis)
    want = n_dev * (n_dev + 1) / 2

    def contribution():
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        return jnp.full((n_elems,), 1.0, jnp.float32) + i

    def verify():
        out = jax.lax.psum(contribution(), axis)
        return _replicate(jnp.max(jnp.abs(out - want)), mesh)

    def timed_step(carry):
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        if carry is None:
            return contribution()
        # mix the previous result back in: every hop stays data-dependent
        # and per-shard distinct, so XLA can neither reorder nor fold it.
        # `+ i` keeps the carry varying over the axis (a bare psum output is
        # replicated, which scan rejects as a carry-type change).
        return jax.lax.psum(contribution() + 1e-6 * carry, axis) + i

    moved = 2 * (n_dev - 1) / n_dev * (n_dev * n_elems * 4)
    return _run(mesh, verify, timed_step, P(axis), moved, n_dev)


def all_gather_probe(mesh: Mesh, axis: str = "tp", n_elems: int = 1 << 18) -> dict[str, Any]:
    """All-gather over ``axis``; every shard must see every contribution."""
    n_dev = _axis_size(mesh, axis)

    def verify():
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        g = jax.lax.all_gather(jnp.full((n_elems,), i, jnp.float32), axis)
        # row r of the gather must hold value r, on every participant
        want = jnp.arange(n_dev, dtype=jnp.float32)[:, None]
        return _replicate(jnp.max(jnp.abs(g - want)), mesh)

    def timed_step(carry):
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        if carry is None:
            return jnp.full((n_elems,), i, jnp.float32)
        g = jax.lax.all_gather(carry + i, axis)       # (n_dev, n_elems)
        return jnp.mean(g, axis=0) + i                # keep carry varying

    moved = (n_dev - 1) / n_dev * (n_dev * n_elems * 4) * n_dev
    return _run(mesh, verify, timed_step, P(axis), moved, n_dev)


def reduce_scatter_probe(mesh: Mesh, axis: str = "tp", n_elems: int = 1 << 18) -> dict[str, Any]:
    """psum_scatter over ``axis`` — the backbone of row-parallel matmuls."""
    n_dev = _axis_size(mesh, axis)

    want = n_dev * (n_dev + 1) / 2

    def contribution():
        # axis-index-dependent so replication analysis can't fold the
        # collective into local math (see psum_probe)
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        return jnp.full((n_dev * n_elems,), 1.0, jnp.float32) + i

    def verify():
        out = jax.lax.psum_scatter(contribution(), axis, tiled=True)
        return _replicate(jnp.max(jnp.abs(out - want)), mesh)

    def timed_step(carry):
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        if carry is None:
            return jnp.full((n_elems,), i, jnp.float32)
        x = contribution() + 1e-6 * jnp.tile(carry, n_dev)
        return jax.lax.psum_scatter(x, axis, tiled=True)

    moved = (n_dev - 1) / n_dev * (n_dev * n_dev * n_elems * 4)
    return _run(mesh, verify, timed_step, P(axis), moved, n_dev)


def ring_permute_probe(mesh: Mesh, axis: str = "sp", n_elems: int = 1 << 18) -> dict[str, Any]:
    """One hop of a ring ``ppermute`` — the primitive under ring attention.

    Long-context sequence parallelism (ring attention) is a chain of these
    neighbour exchanges; a working ring hop at every position proves the ICI
    ring the ``gke-tpu`` placement policy promised actually exists.
    """
    n_dev = _axis_size(mesh, axis)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def verify():
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        payload = jnp.full((n_elems,), 0.0, jnp.float32) + i
        out = jax.lax.ppermute(payload, axis, perm)
        want = (jax.lax.axis_index(axis).astype(jnp.float32) - 1) % n_dev
        return _replicate(jnp.max(jnp.abs(out - want)), mesh)

    def timed_step(carry):
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        if carry is None:
            return jnp.full((n_elems,), 0.0, jnp.float32) + i
        return jax.lax.ppermute(carry + i, axis, perm)

    moved = n_dev * n_elems * 4
    return _run(mesh, verify, timed_step, P(axis), moved, n_dev)


def all_to_all_probe(mesh: Mesh, axis: str = "ep", n_elems: int = 1 << 16) -> dict[str, Any]:
    """All-to-all over ``axis`` — the MoE dispatch/combine collective.

    Expert parallelism routes tokens with exactly this exchange
    (``models/moe.py``'s dispatch/combine einsums lower to it), so a
    slice sold as MoE-capable must prove the all-to-all path, not just
    psum/all-gather. Each participant ``i`` fills row ``r`` of a local
    ``[n, n_elems]`` payload with ``i·n + r`` (per-shard distinct, so
    replication analysis can't fold the collective away); after the
    exchange, row ``j`` must hold ``j·n + i`` — participant ``j``'s
    chunk addressed to ``i`` — on every device.
    """
    n_dev = _axis_size(mesh, axis)

    def contribution():
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        r = jnp.arange(n_dev, dtype=jnp.float32)[:, None]
        return jnp.broadcast_to(i * n_dev + r, (n_dev, n_elems))

    def verify():
        out = jax.lax.all_to_all(contribution(), axis, split_axis=0,
                                 concat_axis=0, tiled=True)
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        want = jnp.arange(n_dev, dtype=jnp.float32)[:, None] * n_dev + i
        return _replicate(jnp.max(jnp.abs(out - want)), mesh)

    def timed_step(carry):
        i = jax.lax.axis_index(axis).astype(jnp.float32)
        if carry is None:
            return contribution()
        # `+ i` keeps each hop's payload per-shard distinct and
        # data-dependent on the previous exchange (see psum_probe)
        return jax.lax.all_to_all(carry + i, axis, split_axis=0,
                                  concat_axis=0, tiled=True)

    # each participant ships (n-1)/n of its local array per hop
    moved = (n_dev - 1) * n_elems * 4 * n_dev
    return _run(mesh, verify, timed_step, P(axis), moved, n_dev)


# ------------------------------------------------- DCN-aware hierarchy


def hierarchical_psum(x, mesh: Mesh, slice_axis: str = "slice",
                      inner_axes: tuple[str, ...] = ("dp",)):
    """DCN-topology-aware all-reduce, for use *inside* ``shard_map``.

    A flat ``psum`` over ``("slice", "dp")`` leaves the schedule to XLA,
    which on a CPU rig (and on backends without megascale's hierarchy
    pass) runs one monolithic ring — every hop as expensive as the
    slowest link, i.e. DCN. This is the explicit Podracer-shaped
    decomposition instead:

    1. **reduce-scatter over the ICI axes** — each of the ``k`` slice
       members ends up owning the slice-local sum of ``1/k`` of the
       vector;
    2. **psum over the slice axis (DCN)** on that ``1/k`` chunk only —
       the cross-slice traffic shrinks by the slice's ICI degree;
    3. **all-gather over the ICI axes** — the broadcast back.

    Elastic by construction: the topology is read from ``mesh`` at
    *trace* time, so a world that re-formed with a different slice count
    (or none — the post-shrink single-slice/degenerate world, where the
    ``slice`` axis is absent or size 1) just re-traces: missing axes
    drop out and the reduction degrades to the plain ICI ``psum``.
    Padding makes any element count divisible by ``k``; results match
    ``jax.lax.psum`` over the same axes exactly up to float summation
    order.
    """
    names = mesh.axis_names
    inner = tuple(a for a in inner_axes if a in names)
    k = 1
    for a in inner:
        k *= mesh.shape[a]
    n_slices = mesh.shape[slice_axis] if slice_axis in names else 1
    if n_slices == 1 or k == 1:
        axes = ((slice_axis,) if slice_axis in names else ()) + inner
        return jax.lax.psum(x, axes) if axes else x
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % k
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # named phases: zero runtime cost (trace-time only), but the XLA
    # device trace (utils/profiling.device_trace) groups each phase's
    # kernels under these names — correlating with the host-side
    # hier_psum_* telemetry spans the probe emits, by name
    with jax.named_scope("hier_psum_ici_reduce_scatter"):
        chunk = jax.lax.psum_scatter(flat, inner, tiled=True)   # ICI
    with jax.named_scope("hier_psum_dcn_psum"):
        chunk = jax.lax.psum(chunk, slice_axis)             # DCN, 1/k data
    with jax.named_scope("hier_psum_ici_all_gather"):
        flat = jax.lax.all_gather(chunk, inner, tiled=True)     # ICI
    if pad:
        flat = flat[:n]
    return flat.reshape(shape)


def hierarchical_psum_probe(mesh: Mesh, slice_axis: str = "slice",
                            inner_axis: str = "dp",
                            n_elems: int = 1 << 16) -> dict[str, Any]:
    """All-reduce over (slice × inner) via :func:`hierarchical_psum`.

    The multislice smoke test's DCN-hierarchy leg: proves the
    reduce-scatter → cross-slice psum → all-gather composition carries a
    correct gradient-shaped reduction on whatever topology the resumed
    world actually has (slice axis present, absent, or size 1 — the
    probe itself is elastic the same way the collective is).
    """
    names = mesh.axis_names
    axes = tuple(a for a in ((slice_axis,) if slice_axis in names else ())
                 + ((inner_axis,) if inner_axis in names else ()))
    if not axes:
        raise ValueError(
            f"mesh {names} has neither {slice_axis!r} nor {inner_axis!r}")
    m = 1
    for a in axes:
        m *= mesh.shape[a]
    want = m * (m + 1) / 2

    def combined_index():
        i = jnp.int32(0)
        for a in axes:
            i = i * mesh.shape[a] + jax.lax.axis_index(a)
        return i.astype(jnp.float32)

    def contribution():
        return jnp.full((n_elems,), 1.0, jnp.float32) + combined_index()

    def verify():
        out = hierarchical_psum(contribution(), mesh, slice_axis,
                                (inner_axis,))
        return _replicate(jnp.max(jnp.abs(out - want)), mesh)

    def timed_step(carry):
        i = combined_index()
        if carry is None:
            return contribution()
        # `+ i` keeps the carry per-shard distinct (see psum_probe)
        return hierarchical_psum(contribution() + 1e-6 * carry, mesh,
                                 slice_axis, (inner_axis,)) + i

    k = mesh.shape[inner_axis] if inner_axis in names else 1
    s = mesh.shape[slice_axis] if slice_axis in names else 1
    data = m * n_elems * 4
    # per the hierarchy: RS + AG ride ICI on the full vector, the DCN
    # all-reduce moves only the 1/k chunk per slice pair
    ici = 2 * (k - 1) / k * data if k > 1 else 0.0
    dcn = 2 * (s - 1) / s * (data / max(k, 1)) if s > 1 else 0.0
    moved = (ici + dcn) or 2 * (m - 1) / m * data
    from ..telemetry import get_registry

    reg = get_registry()
    if reg.enabled:
        # the host-side ICI-vs-DCN phase record: one span for the probe
        # with the phase byte split in args; the per-phase device kernels
        # correlate by the hier_psum_* named_scope names inside the trace
        with reg.span("hier_psum_probe", participants=m,
                      ici_bytes=ici, dcn_bytes=dcn,
                      slices=s, inner=k):
            out = _run(mesh, verify, timed_step, P(pspec_axes(axes)),
                       moved, m)
        reg.gauge("hier_psum_gibps").set(
            moved / max(out["seconds"], 1e-9) / (1 << 30))
    else:
        out = _run(mesh, verify, timed_step, P(pspec_axes(axes)), moved, m)
    out["ici_bytes"] = ici
    out["dcn_bytes"] = dcn
    return out


ALL_PROBES = {
    "psum": psum_probe,
    "all_gather": all_gather_probe,
    "reduce_scatter": reduce_scatter_probe,
    "ring_permute": ring_permute_probe,
    "all_to_all": all_to_all_probe,
}
