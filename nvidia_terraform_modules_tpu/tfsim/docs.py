# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""README generation: the offline stand-in for ``terraform-docs``.

The reference's contributor workflow regenerates each module README's API
tables with terraform-docs (``/root/reference/CONTRIBUTING.md:14``) — the
README *is* the module's API documentation (SURVEY.md L7). This module
renders the same tables (requirements, resources, inputs, outputs) from
tfsim's parsed ``Module`` and splices them between marker comments, so CI can
assert the docs never drift from ``variables.tf``/``outputs.tf``:

    <!-- BEGIN_TF_DOCS -->
    ...generated, do not edit by hand...
    <!-- END_TF_DOCS -->
"""

from __future__ import annotations

import json

from . import ast as A
from .module import Module

BEGIN = "<!-- BEGIN_TF_DOCS -->"
END = "<!-- END_TF_DOCS -->"


def _render_default(expr: A.Expr | None) -> str | None:
    """Best-effort literal rendering of a variable default, JSON-style."""
    if expr is None:
        return None
    v = _literal(expr)
    if v is _RAW:
        return "`<expression>`"
    return f"`{json.dumps(v)}`"


_RAW = object()


def _literal(e: A.Expr):
    if isinstance(e, A.Literal):
        return e.value
    if isinstance(e, A.TupleExpr):
        items = [_literal(x) for x in e.items]
        return _RAW if any(x is _RAW for x in items) else items
    if isinstance(e, A.ObjectExpr):
        out = {}
        for it in e.items:
            k = it.key.value if isinstance(it.key, A.Literal) else _RAW
            v = _literal(it.value)
            if k is _RAW or v is _RAW:
                return _RAW
            out[str(k)] = v
        return out
    return _RAW


def _md_escape(text: str) -> str:
    return text.replace("\n", " ").replace("|", "\\|").strip()


def generate_docs(mod: Module) -> str:
    """Render the generated-docs block (without the BEGIN/END markers)."""
    lines: list[str] = []
    add = lines.append

    # ---- requirements ------------------------------------------------
    add("## Requirements")
    add("")
    add("| Name | Version |")
    add("|------|---------|")
    add(f"| terraform | `{mod.required_version or 'any'}` |")
    for name in sorted(mod.required_providers):
        spec = mod.required_providers[name]
        ver = spec.get("version", "any")
        src = spec.get("source", name)
        add(f"| {name} ({src}) | `{ver}` |")
    add("")

    # ---- resources ---------------------------------------------------
    managed = sorted(mod.resources)
    data = sorted(mod.data_sources)
    if managed or data:
        add("## Resources")
        add("")
        add("| Address | Defined in |")
        add("|---------|------------|")
        for addr in managed:
            r = mod.resources[addr]
            add(f"| `{addr}` | `{r.file}:{r.line}` |")
        for addr in data:
            r = mod.data_sources[addr]
            add(f"| `{addr}` | `{r.file}:{r.line}` |")
        add("")

    # ---- inputs ------------------------------------------------------
    if mod.variables:
        add("## Inputs")
        add("")
        add("| Name | Description | Type | Default | Required |")
        add("|------|-------------|------|---------|:--------:|")
        for name in sorted(mod.variables):
            v = mod.variables[name]
            desc = _md_escape(v.description or "n/a")
            vtype = f"`{v.type}`" if v.type else "`any`"
            default = _render_default(v.default)
            required = "yes" if default is None else "no"
            add(f"| {name} | {desc} | {vtype} | {default or 'n/a'} | {required} |")
        add("")

    # ---- outputs -----------------------------------------------------
    if mod.outputs:
        add("## Outputs")
        add("")
        add("| Name | Description | Sensitive |")
        add("|------|-------------|:---------:|")
        for name in sorted(mod.outputs):
            o = mod.outputs[name]
            desc = _md_escape(o.description or "n/a")
            add(f"| {name} | {desc} | {'yes' if o.sensitive else ''} |")
        add("")

    return "\n".join(lines).rstrip() + "\n"


class DocsError(ValueError):
    pass


def inject_docs(readme_text: str, mod: Module) -> str:
    """Replace the text between the BEGIN/END markers with generated docs."""
    if BEGIN not in readme_text or END not in readme_text:
        raise DocsError(
            f"README has no {BEGIN} / {END} markers to inject into"
        )
    head, rest = readme_text.split(BEGIN, 1)
    _, tail = rest.split(END, 1)
    return f"{head}{BEGIN}\n{generate_docs(mod)}{END}{tail}"


def check_readme(module_dir: str) -> bool:
    """True iff ``module_dir/README.md`` is in sync with the module."""
    import os

    from .module import load_module

    readme = os.path.join(module_dir, "README.md")
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    return inject_docs(text, load_module(module_dir)) == text


def update_readme(module_dir: str, write: bool = True) -> bool:
    """Regenerate the docs block. Returns True if it was already in sync."""
    import os

    from .module import load_module

    readme = os.path.join(module_dir, "README.md")
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    new = inject_docs(text, load_module(module_dir))
    if new == text:
        return True
    if write:
        with open(readme, "w", encoding="utf-8") as f:
            f.write(new)
    return False


def main(argv: list[str] | None = None) -> int:
    """CLI: ``python -m nvidia_terraform_modules_tpu.tfsim.docs [-check] DIR...``"""
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="tfsim docs")
    ap.add_argument("-check", action="store_true",
                    help="fail (exit 3) if any README is out of sync")
    ap.add_argument("dirs", nargs="+")
    args = ap.parse_args(argv)

    drift = 0
    for d in args.dirs:
        if args.check:
            if not check_readme(d):
                print(f"{d}/README.md: docs block out of sync", file=sys.stderr)
                drift += 1
        elif not update_readme(d):
            print(f"{d}/README.md: updated")
    return 3 if drift else 0


if __name__ == "__main__":
    raise SystemExit(main())
