#!/usr/bin/env python3
# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Self-contained in-cluster TPU smoke test (single-file Job payload).

This is the deployable bundle of nvidia_terraform_modules_tpu.smoketest: the
same env contract, JSON-line output, and exit-code semantics, with zero
package dependencies beyond jax — it is mounted from a ConfigMap into any
JAX-capable image (see smoketest.tf).

Env contract (injected by the gke-tpu module):
  TPU_SMOKETEST_EXPECTED_DEVICES  chips the whole world must expose
  TPU_SMOKETEST_LEVEL             psum | probes | burnin | full
  TPU_SMOKETEST_HOSTS             TOTAL hosts in the world (all slices)
  TPU_SMOKETEST_PROCESS_BASE      this slice's host-index offset (0 default)
  TPU_SMOKETEST_SLICES            slice count; > 1 adds a cross-slice (DCN)
                                  psum check
  TPU_SMOKETEST_COORDINATOR       headless-service DNS of slice-0 pod 0
  TPU_SMOKETEST_INIT_TIMEOUT      seconds to wait for the full world (300)
  JOB_COMPLETION_INDEX            set by Kubernetes on Indexed Jobs

Prints ONE JSON line; exit 0 iff every check passed. `terraform apply`
blocks on this via wait_for_completion — apply succeeding IS the test
passing (north star: BASELINE.json).
"""

import functools
import json
import os
import sys
import time


def main() -> int:
    t0 = time.perf_counter()
    out = {"ok": False}

    level = os.environ.get("TPU_SMOKETEST_LEVEL", "probes")
    if level not in ("psum", "probes", "burnin", "full"):
        out["error"] = f"unknown level {level!r}"
        print(json.dumps(out), flush=True)
        return 2

    hosts = int(os.environ.get("TPU_SMOKETEST_HOSTS", "1"))
    idx = int(os.environ.get("JOB_COMPLETION_INDEX", "0")) + \
        int(os.environ.get("TPU_SMOKETEST_PROCESS_BASE", "0"))
    out.update({"level": level, "process_id": idx, "num_processes": hosts})

    import jax

    if not hasattr(jax, "shard_map"):
        # older jax ships shard_map only under experimental (pre top-level
        # promotion); alias it so the bundle stays a zero-dependency file
        # that runs on either image generation
        from jax.experimental.shard_map import shard_map as _shard_map

        jax.shard_map = _shard_map

    if hosts > 1:
        # older jax defaults CPU cross-process collectives to "none"
        # (every multi-process CPU computation then fails); newer jax
        # defaults to gloo and may drop the knob entirely. Older jax
        # exposes the value only via config._read()/config.values, so an
        # operator's explicit choice (e.g. mpi) is read through whichever
        # surface exists before gloo is selected.
        current = None
        for read in (
                lambda: jax.config._read(
                    "jax_cpu_collectives_implementation"),
                lambda: jax.config.values[
                    "jax_cpu_collectives_implementation"],
                lambda: getattr(jax.config,
                                "jax_cpu_collectives_implementation")):
            try:
                current = read()
                break
            except Exception:
                continue
        if current in (None, "none"):
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except (AttributeError, ValueError):
                try:  # oldest surface: the Flag object on xla_bridge
                    from jax._src import xla_bridge as _xb

                    flag = getattr(_xb, "CPU_COLLECTIVES_IMPLEMENTATION",
                                   None)
                    if flag is not None and flag.value in (None, "none"):
                        flag._set("gloo")
                except Exception:
                    pass
        coord = os.environ["TPU_SMOKETEST_COORDINATOR"]
        if ":" not in coord:
            coord = f"{coord}:8476"
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=hosts,
            process_id=idx,
            initialization_timeout=int(
                os.environ.get("TPU_SMOKETEST_INIT_TIMEOUT", "300")),
        )

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    out["devices"] = n
    out["device_kind"] = devices[0].device_kind

    expected = os.environ.get("TPU_SMOKETEST_EXPECTED_DEVICES")
    if expected is not None and int(expected) != n:
        out["expected_devices"] = int(expected)
        out["device_count_ok"] = False
        print(json.dumps(out), flush=True)
        return 1
    out["device_count_ok"] = True

    mesh = Mesh(np.asarray(devices), ("x",))
    shard = functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(), out_specs=P("x"))

    # Multi-host discipline: inputs are generated INSIDE the sharded
    # computation (no host→global transfers) and results are verified
    # through each process's addressable shards only — a jax.Array from a
    # multi-host mesh spans devices this process cannot fetch.
    def local_values(arr):
        shards = sorted(
            arr.addressable_shards,
            key=lambda s: s.index[0].start if s.index and s.index[0].start else 0,
        )
        return np.concatenate([np.asarray(s.data) for s in shards])

    # 1. the north-star psum: every chip contributes 1, sum must equal n
    @jax.jit
    @shard
    def allreduce():
        return jax.lax.psum(jnp.ones((1024,), jnp.float32), "x")

    out["psum_ok"] = bool(np.allclose(local_values(allreduce()), float(n)))
    ok = out["psum_ok"]

    # 1b. cross-slice (DCN) psum: a reduction over the slice axis proves the
    # inter-slice path carries collectives, not just the in-slice ICI ring.
    # Devices group by slice_index metadata when the runtime provides it
    # (real multi-slice); contiguous grouping otherwise (process-major
    # enumeration puts each slice's hosts together).
    slices = int(os.environ.get("TPU_SMOKETEST_SLICES", "1"))
    if slices > 1:
        out["slices"] = slices
        if n % slices != 0:
            # a bad slice config must FAIL the contract, not silently skip
            # the one check that proves DCN (matches the package runner's
            # plan_multislice ValueError policy)
            out["slices_error"] = (
                f"{n} devices do not divide into {slices} slices")
            out["dcn_psum_ok"] = False
            ok = False
        elif ok:
            if all(getattr(d, "slice_index", None) is not None
                   for d in devices):
                devs = sorted(devices, key=lambda d: (d.slice_index, d.id))
            else:
                devs = list(devices)
            per = n // slices
            mesh2 = Mesh(
                np.asarray(devs).reshape(slices, per), ("slice", "x"))

            @jax.jit
            @functools.partial(
                jax.shard_map, mesh=mesh2, in_specs=(),
                out_specs=P("slice", "x"))
            def dcn_psum():
                return jax.lax.psum(jnp.ones((1, 256), jnp.float32), "slice")

            shards = dcn_psum().addressable_shards
            out["dcn_psum_ok"] = bool(all(
                np.allclose(np.asarray(s.data), float(slices))
                for s in shards))
            ok = ok and out["dcn_psum_ok"]

    # 2. collective probes over the same ring — correctness plus a measured
    # bandwidth figure per host in the Job log (operators grep the JSON the
    # way the reference's runbooks grep `kubectl get po`)
    def timed(fn, nbytes):
        # warm-up must SYNCHRONIZE (dispatch is async — an un-awaited warm
        # call would still be executing inside the timed region) so the
        # figure is transport, not compile or queueing
        jax.block_until_ready(fn())
        t = time.perf_counter()
        r = jax.block_until_ready(fn())
        dt = max(time.perf_counter() - t, 1e-9)
        return r, round(nbytes / dt / (1 << 30), 3)

    if level in ("probes", "burnin", "full") and ok and n > 1:
        @jax.jit
        @shard
        def ring_hop():
            i = jax.lax.axis_index("x").astype(jnp.float32)
            payload = jnp.full((1 << 16,), 0.0, jnp.float32) + i
            return jax.lax.ppermute(
                payload, "x", [(j, (j + 1) % n) for j in range(n)])

        hop_arr, out["ring_gibps"] = timed(ring_hop, n * (1 << 16) * 4)
        hop = local_values(hop_arr).reshape(-1, 1 << 16)
        # this process's shards hold positions [idx*k, (idx+1)*k) of the ring
        k = hop.shape[0]
        mine = (np.arange(idx * k, (idx + 1) * k, dtype=np.float32) - 1) % n
        out["ring_ok"] = bool(np.allclose(hop, mine[:, None]))

        @jax.jit
        @shard
        def gather():
            i = jax.lax.axis_index("x").astype(jnp.float32)
            g = jax.lax.all_gather(jnp.full((1 << 14,), i, jnp.float32), "x")
            # every position sees every contribution; re-shard the sum so
            # out_specs stays P("x")
            return jnp.sum(g, axis=0)

        g_arr, out["all_gather_gibps"] = timed(
            gather, n * (n - 1) * (1 << 14) * 4)
        g = local_values(g_arr)
        expect = sum(range(n))  # 0+1+...+(n-1) at every element
        out["all_gather_ok"] = bool(np.allclose(g, float(expect)))
        ok = ok and out["ring_ok"] and out["all_gather_ok"]

        # all-to-all — the MoE dispatch/combine collective: participant i
        # fills row r with i·n + r; after the exchange row j must hold
        # j·n + i (participant j's chunk addressed to i). Verified via a
        # replicated error scalar (every process may fetch it).
        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(), out_specs=P())
        def alltoall_err():
            i = jax.lax.axis_index("x").astype(jnp.float32)
            r = jnp.arange(n, dtype=jnp.float32)[:, None]
            payload = jnp.broadcast_to(i * n + r, (n, 1 << 12))
            got = jax.lax.all_to_all(payload, "x", split_axis=0,
                                     concat_axis=0, tiled=True)
            want = jnp.arange(n, dtype=jnp.float32)[:, None] * n + i
            return jax.lax.pmax(jnp.max(jnp.abs(got - want)), "x")

        a2a_err, out["alltoall_gibps"] = timed(
            alltoall_err, (n - 1) * (1 << 12) * 4 * n)
        out["alltoall_ok"] = bool(float(np.asarray(a2a_err)) == 0.0)
        ok = ok and out["alltoall_ok"]

    # 3. burn-in: a few bf16 matmul train steps must reduce a quadratic loss.
    # With TPU_SMOKETEST_CHECKPOINT_DIR set (spot slices: the pod may be
    # preempted and recreated; the Job mounts a PVC at that path), the
    # global step and weights resume from a per-process .npz checkpoint —
    # dependency-free here; the installable package runner uses orbax
    # (sharded, gs://-capable) for the same contract. Each step saves
    # atomically; a SUCCESSFUL run removes its checkpoint so the next fresh
    # Job starts at step 0. Any checkpoint I/O failure — including a
    # corrupt/truncated file (BadZipFile/KeyError, not just OSError) —
    # fails the suite through the JSON contract, never a bare traceback.
    if level in ("burnin", "full") and ok:
        ckpt_dir = os.environ.get("TPU_SMOKETEST_CHECKPOINT_DIR")
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (256, 256), jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (1024, 256), jnp.bfloat16)
        global_step = 0
        ckpt_path = None
        if ckpt_dir and "://" in ckpt_dir:
            # remote URIs need the installable package's orbax backend; this
            # dependency-free bundle would "succeed" onto a literal local
            # ./gs:/… directory on ephemeral disk and never actually resume.
            # The module's variable validation requires a custom command
            # (package-bearing image) for gs:// — reaching here means the
            # Job is running the bundle against a remote URI: fail loudly.
            out["burnin_checkpoint_ok"] = False
            out["checkpoint_error"] = (
                f"bundled payload cannot checkpoint to remote URI "
                f"{ckpt_dir!r}; run the nvidia_terraform_modules_tpu "
                f"package (smoketest.command) or use a PVC-backed path")
            print(json.dumps(out), flush=True)
            return 1
        try:
            if ckpt_dir:
                os.makedirs(ckpt_dir, exist_ok=True)
                ckpt_path = os.path.join(ckpt_dir, f"burnin_p{idx}.npz")
                # a preemption between savez(tmp) and replace orphans the
                # tmp file; sweep it here so it can't accumulate on the PVC
                if os.path.exists(ckpt_path + ".tmp.npz"):
                    os.remove(ckpt_path + ".tmp.npz")
                if os.path.exists(ckpt_path):
                    data = np.load(ckpt_path)
                    w_loaded = data["w"]
                    # a stale file from a different script revision loads
                    # cleanly but would crash the jitted step with a bare
                    # shape TypeError — keep it inside the JSON contract
                    if w_loaded.shape != w.shape or \
                            w_loaded.dtype != w.dtype:
                        raise ValueError(
                            f"stale checkpoint: w is "
                            f"{w_loaded.dtype}{w_loaded.shape}, expected "
                            f"{w.dtype}{tuple(w.shape)}")
                    w = jnp.asarray(w_loaded)
                    global_step = int(data["step"])
                    out["burnin_resumed_step"] = global_step
        except Exception as exc:
            out["burnin_checkpoint_ok"] = False
            out["checkpoint_error"] = f"restore: {exc}"
            print(json.dumps(out), flush=True)
            return 1

        def loss_fn(w, x):
            y = (x @ w.astype(jnp.bfloat16)).astype(jnp.float32)
            return jnp.mean(jnp.square(y))

        @jax.jit
        def step(w, x):
            l, g = jax.value_and_grad(loss_fn)(w, x)
            return w - 0.05 * g, l

        def save(step_no, weights):
            # atomic: a preemption mid-write must leave the previous
            # checkpoint restorable, never a truncated file
            tmp = ckpt_path + ".tmp.npz"
            np.savez(tmp, w=np.asarray(weights), step=step_no)
            os.replace(tmp, ckpt_path)

        losses = []
        for _ in range(5):
            w, l = step(w, x)
            losses.append(float(l))
            global_step += 1
            if ckpt_path:
                try:
                    save(global_step, w)
                except Exception as exc:
                    out["burnin_checkpoint_ok"] = False
                    out["checkpoint_error"] = f"save: {exc}"
                    ok = False
                    break
        if ckpt_path and ok:
            out["burnin_checkpoint_saved"] = global_step
        out["burnin_first_loss"] = round(losses[0], 5)
        out["burnin_last_loss"] = round(losses[-1], 5)
        out["burnin_step"] = global_step
        out["burnin_ok"] = len(losses) == 5 and losses[-1] < losses[0]
        ok = ok and out["burnin_ok"]
        if ckpt_path and ok:
            try:
                os.remove(ckpt_path)   # validated: next fresh Job starts at 0
                # int (files removed), matching the package runner's
                # step-count semantics so both verdicts parse uniformly
                out["burnin_checkpoint_cleared"] = 1
            except Exception as exc:
                out["burnin_checkpoint_ok"] = False
                out["checkpoint_error"] = f"clear: {exc}"
                ok = False

    # 4. full: the ep/pp fabric legs the dense burn-in never exercises —
    # a capacity-routed MoE step whose dispatch/combine are real
    # all_to_alls (one expert per chip), and a 2-stage pipeline step whose
    # forward AND backward cross the stage ppermute. Both train
    # loss-decreasing, so autodiff through the fabric is proven, not just
    # transport. Single chip: skipped with an explicit marker (no fabric
    # to prove), never a vacuous pass.
    if level == "full" and ok:
        if n < 2:
            out["full_skipped"] = "ep/pp fabric needs >= 2 devices"
        else:
            d, hdim, t_loc, cap = 16, 32, 32, 96
            E = n                           # one expert per device

            def moe_loss(wr, w1, w2):
                i = jax.lax.axis_index("x")
                x = jnp.sin(jnp.arange(t_loc * d, dtype=jnp.float32)
                            .reshape(t_loc, d) * 0.01 * (i + 1.0))
                logits = x @ wr                         # [t, E]
                gate = jax.nn.softmax(logits, axis=-1)
                sel = jnp.argmax(logits, axis=-1)
                onehot = jax.nn.one_hot(sel, E)         # [t, E]
                pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
                keep = onehot * (pos < cap)
                slot = jax.nn.one_hot(
                    (pos * keep).astype(jnp.int32), cap) * keep[..., None]
                disp = slot                              # [t, E, cap]
                xs = jnp.einsum("tec,td->ecd", disp, x)  # [E, cap, d]
                xs = jax.lax.all_to_all(xs, "x", split_axis=0,
                                        concat_axis=0, tiled=True)
                ys = jnp.tanh(xs @ w1[0]) @ w2[0]        # local expert
                ys = jax.lax.all_to_all(ys, "x", split_axis=0,
                                        concat_axis=0, tiled=True)
                g = jnp.einsum("te,tec->tec", gate, disp)
                y = jnp.einsum("tec,ecd->td", g, ys)
                loss = jnp.mean(jnp.square(y - x))
                return jax.lax.pmean(loss, "x")

            @jax.jit
            @functools.partial(
                jax.shard_map, mesh=mesh,
                in_specs=(P(), P("x"), P("x")), out_specs=(P(), P("x"),
                                                           P("x"), P()))
            def moe_step(wr, w1, w2):
                loss, grads = jax.value_and_grad(moe_loss, (0, 1, 2))(
                    wr, w1, w2)
                grads = (jax.lax.pmean(grads[0], "x"),) + grads[1:]
                new = [p - 0.5 * g for p, g in zip((wr, w1, w2), grads)]
                return (*new, loss)

            k = jax.random.PRNGKey(7)
            wr = jax.random.normal(k, (d, E), jnp.float32) * 0.1
            w1 = jax.random.normal(k, (E, d, hdim), jnp.float32) * 0.1
            w2 = jax.random.normal(k, (E, hdim, d), jnp.float32) * 0.1
            moe_losses = []
            for _ in range(3):
                wr, w1, w2, ml = moe_step(wr, w1, w2)
                moe_losses.append(float(np.asarray(ml)))
            out["moe_first_loss"] = round(moe_losses[0], 5)
            out["moe_last_loss"] = round(moe_losses[-1], 5)
            out["moe_ok"] = moe_losses[-1] < moe_losses[0]
            ok = ok and out["moe_ok"]

            if n % 2:
                out["pipeline_skipped"] = f"{n} devices do not split 2 ways"
            else:
                if all(getattr(dv, "slice_index", None) is not None
                       for dv in devices):
                    pdevs = sorted(devices,
                                   key=lambda dv: (dv.slice_index, dv.id))
                else:
                    pdevs = list(devices)
                pmesh = Mesh(np.asarray(pdevs).reshape(2, n // 2),
                             ("pp", "x"))
                m, b = 4, 8

                def pipe_loss(ws):
                    s = jax.lax.axis_index("pp")
                    j = jax.lax.axis_index("x").astype(jnp.float32)
                    xs = jnp.sin(
                        jnp.arange(m * b * d, dtype=jnp.float32)
                        .reshape(m, b, d) * 0.01 * (j + 1.0))
                    recv = jnp.zeros((b, d), jnp.float32)
                    total = 0.0
                    for t in range(m + 1):       # m microbatches + drain
                        state = jnp.where(s == 0, xs[min(t, m - 1)], recv)
                        h = jnp.tanh(state @ ws[0])
                        done = (s == 1) & (1 <= t)
                        total = total + jnp.where(
                            done, jnp.mean(jnp.square(h)), 0.0)
                        recv = jax.lax.ppermute(h, "pp", [(0, 1)])
                    return jax.lax.psum(total, ("pp", "x")) / (
                        m * pmesh.shape["x"])

                @jax.jit
                @functools.partial(
                    jax.shard_map, mesh=pmesh, in_specs=(P("pp"),),
                    out_specs=(P("pp"), P()))
                def pipe_step(ws):
                    loss, gw = jax.value_and_grad(pipe_loss)(ws)
                    gw = jax.lax.pmean(gw, "x")   # data-parallel reduce
                    return ws - 0.2 * gw, loss

                pws = jax.random.normal(
                    jax.random.PRNGKey(8), (2, d, d), jnp.float32) * 0.3
                pws = jax.device_put(
                    pws, jax.sharding.NamedSharding(pmesh, P("pp")))
                pipe_losses = []
                for _ in range(3):
                    pws, pl = pipe_step(pws)
                    pipe_losses.append(float(np.asarray(pl)))
                out["pipeline_first_loss"] = round(pipe_losses[0], 5)
                out["pipeline_last_loss"] = round(pipe_losses[-1], 5)
                out["pipeline_ok"] = pipe_losses[-1] < pipe_losses[0]
                ok = ok and out["pipeline_ok"]

    out["ok"] = bool(ok)
    out["seconds"] = round(time.perf_counter() - t0, 3)
    print(json.dumps(out), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
