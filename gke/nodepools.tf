# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Node pools (L3): one general-purpose CPU pool, one GPU pool.
#
# Capability parity with google_container_node_pool.cpu_nodes / gpu_nodes
# (/root/reference/gke/main.tf:60-151): autoscaling bounds, disk shaping,
# spot capacity, logging/monitoring scopes, GKE_METADATA workload metadata,
# and guest_accelerator on the GPU pool. Shared config is factored into a
# local instead of being duplicated across the two pools.

locals {
  node_oauth_scopes = [
    "https://www.googleapis.com/auth/logging.write",
    "https://www.googleapis.com/auth/monitoring",
    "https://www.googleapis.com/auth/devstorage.read_only",
  ]
}

resource "google_container_node_pool" "cpu" {
  name     = "${var.cluster_name}-cpu"
  project  = var.project_id
  cluster  = google_container_cluster.this.name
  location = local.cluster_location

  node_locations     = local.pool_zones
  initial_node_count = var.cpu_pool.initial_nodes

  autoscaling {
    min_node_count = var.cpu_pool.min_nodes
    max_node_count = var.cpu_pool.max_nodes
  }

  node_config {
    machine_type = var.cpu_pool.machine_type
    disk_size_gb = var.cpu_pool.disk_size_gb
    disk_type    = var.cpu_pool.disk_type
    image_type   = var.cpu_pool.image_type
    spot         = var.cpu_pool.spot
    labels       = var.cpu_pool.labels

    oauth_scopes = local.node_oauth_scopes

    workload_metadata_config {
      mode = "GKE_METADATA"
    }
  }

  timeouts {
    create = "30m"
    update = "20m"
  }
}

resource "google_container_node_pool" "gpu" {
  count = var.gpu_pool.enabled ? 1 : 0

  name     = "${var.cluster_name}-gpu"
  project  = var.project_id
  cluster  = google_container_cluster.this.name
  location = local.cluster_location

  node_locations     = local.pool_zones
  initial_node_count = var.gpu_pool.initial_nodes

  autoscaling {
    min_node_count = var.gpu_pool.min_nodes
    max_node_count = var.gpu_pool.max_nodes
  }

  node_config {
    machine_type = var.gpu_pool.machine_type
    disk_size_gb = var.gpu_pool.disk_size_gb
    disk_type    = var.gpu_pool.disk_type
    image_type   = var.gpu_pool.image_type
    spot         = var.gpu_pool.spot

    labels = merge(var.gpu_pool.labels, { "accelerator" = var.gpu_pool.gpu_type })

    guest_accelerator {
      type  = var.gpu_pool.gpu_type
      count = var.gpu_pool.gpu_count
    }

    oauth_scopes = local.node_oauth_scopes

    workload_metadata_config {
      mode = "GKE_METADATA"
    }
  }

  timeouts {
    create = "30m"
    update = "20m"
  }
}
