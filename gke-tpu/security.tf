# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Control-plane security (L2): secrets-at-rest CMEK + group-based RBAC.
#
# Capability parity with the two reference features that had no GKE
# analogue here until now (round-2 VERDICT item 4):
#
# * /root/reference/eks/main.tf:64-72 — an aws_kms_key with rotation
#   encrypting cluster secrets. GKE's equivalent is application-layer
#   etcd encryption (database_encryption ENCRYPTED + a Cloud KMS key).
#   When no key is brought, the module creates keyring + key with the
#   same 90-day rotation posture, and grants the GKE service agent
#   EncrypterDecrypter on exactly that key — without the grant the
#   control plane cannot unwrap with the CMEK and creation fails.
# * /root/reference/aks/main.tf:36-40 — AAD admin groups wired into the
#   control plane. GKE's equivalent is Google Groups for RBAC
#   (authenticator_groups_config), letting RoleBindings name groups.

data "google_project" "this" {
  project_id = var.project_id
}

locals {
  create_kms_key = (var.database_encryption.enabled &&
    var.database_encryption.kms_key_name == null)
  secrets_kms_key = (!var.database_encryption.enabled ? null :
    (var.database_encryption.kms_key_name != null ?
      var.database_encryption.kms_key_name : google_kms_crypto_key.secrets[0].id))
}

resource "google_kms_key_ring" "secrets" {
  count = local.create_kms_key ? 1 : 0

  name     = "${var.cluster_name}-secrets"
  project  = var.project_id
  location = var.region
}

resource "google_kms_crypto_key" "secrets" {
  count = local.create_kms_key ? 1 : 0

  name            = "${var.cluster_name}-etcd"
  key_ring        = google_kms_key_ring.secrets[0].id
  purpose         = "ENCRYPT_DECRYPT"
  rotation_period = var.database_encryption.key_rotation_period

  lifecycle {
    # a destroyed key makes every secret it wrapped unrecoverable; force
    # the operator to detach it from state instead of deleting it
    prevent_destroy = true
  }
}

resource "google_kms_crypto_key_iam_member" "gke_agent" {
  count = var.database_encryption.enabled ? 1 : 0

  crypto_key_id = local.secrets_kms_key
  role          = "roles/cloudkms.cryptoKeyEncrypterDecrypter"
  member        = "serviceAccount:service-${data.google_project.this.number}@container-engine-robot.iam.gserviceaccount.com"
}
