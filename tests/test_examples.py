"""Golden-plan tests for the examples/cnpack compositions.

These exercise tfsim's recursive module simulation: the example root modules
call the real gke / gke-tpu modules via `source = "../../"` — the same
integration-fixture role the reference's examples play (SURVEY.md §2.4).
"""

import os

import pytest

from nvidia_terraform_modules_tpu.tfsim import (
    load_module,
    simulate_plan,
    validate_module,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("path", [
    "gke/examples/cnpack",
    "gke-tpu/examples/cnpack",
])
def test_examples_validate_clean(path):
    findings = validate_module(load_module(os.path.join(ROOT, path)))
    assert findings == [], [str(f) for f in findings]


def test_tpu_example_plans_slice_and_identity():
    plan = simulate_plan(
        os.path.join(ROOT, "gke-tpu", "examples", "cnpack"),
        {"project_id": "proj-y"},
    )
    addrs = set(plan.instances)
    # child module resources planned through the wrap
    assert ('module.tpu_cluster.google_container_node_pool.'
            'tpu_slice["default"]') in addrs
    assert "module.tpu_cluster.kubernetes_job_v1.tpu_smoketest[0]" in addrs
    # observability identity
    assert "google_service_account.prometheus" in addrs
    assert "google_service_account_iam_member.wi_binding" in addrs
    wi = plan.instance("google_service_account_iam_member.wi_binding")
    assert "tpu-monitoring/tpu-prometheus" in wi.attrs["member"]
    assert plan.outputs["monitoring_namespace"] == "tpu-monitoring"
    assert len(plan.outputs["tpu_metric_types"]) >= 4
    # slice facts surface through the wrap
    assert plan.outputs["tpu_slices"]["default"]["total_chips"] == 8


def test_gpu_example_plans_cluster_and_identity():
    plan = simulate_plan(
        os.path.join(ROOT, "gke", "examples", "cnpack"),
        {"project_id": "proj-y"},
    )
    addrs = set(plan.instances)
    assert "module.gpu_cluster.google_container_cluster.this" in addrs
    assert "module.gpu_cluster.helm_release.gpu_operator[0]" in addrs
    assert "google_project_iam_member.metric_writer" in addrs
    assert plan.outputs["monitoring_namespace"] == "nvidia-monitoring"
