# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
output "cluster_name" {
  description = "Cluster carrying the multi-slice fleet."
  value       = module.tpu_fleet.cluster_name
}

output "tpu_slices" {
  description = "Derived facts per slice (machine type, hosts, chips, topology)."
  value       = module.tpu_fleet.tpu_slices
}

output "total_tpu_chips" {
  description = "Chips across the whole fleet (both slices)."
  value       = module.tpu_fleet.total_tpu_chips
}

output "smoketest_job" {
  description = "The multislice validation Job gating the apply."
  value       = module.tpu_fleet.smoketest_job
}
