# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Test rig: force an 8-device virtual CPU platform BEFORE jax initialises.

This mirrors the SURVEY §4 implication: the reference tests nothing without a
live cloud; we exercise every collective/sharding path on a virtual mesh
(XLA host-platform device count), so `pytest` needs no TPU and no cloud.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at a TPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some rigs pre-import jax (sitecustomize) with a TPU platform already chosen;
# the backend is lazy, so a config update before first use still wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def jax8():
    import jax

    assert len(jax.devices()) == 8, "virtual 8-device CPU platform not active"
    return jax


@pytest.fixture(scope="session")
def repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Fast/slow test profile (CONTRIBUTING: the edit loop runs `-m "not slow"`).
#
# Tests here measured >= 4 s on the CI CPU rig (`pytest --durations`) — the
# gradient-equivalence, multi-step-training, and interpreter-mode pallas
# suites. They are marked `slow` centrally so the fast profile stays under
# two minutes; CI runs everything. Regenerate after perf-relevant test
# changes with:
#   pytest tests/ -q -m "not slow" --durations=0 | awk '$1+0>=4' ...
# (test_manifest_is_fresh below fails loudly on renamed/deleted entries).
SLOW_TESTS = frozenset({
    # ISSUE 15 tier-1 budget audit: the suite re-measured at 944 s
    # against the 870 s timeout (the CI rig runs ~15% slower than the
    # PR 14 measurement), so the three heaviest entries move to the
    # slow profile. Each keeps tier-1 coverage of its subsystem:
    # elastic resume keeps the seeded SIGKILL + corrupted-newest
    # tier-1 cases (the 2-proc gloo world shrink/grow case alone cost
    # 217 s); checkpoint corruption keeps the on-resume quarantine
    # tier-1 case; the ring pipeline keeps the fused-vs-dense sharded
    # parity pair and the flash-level bitwise pipeline pins.
    "tests/test_chaos_resume.py::"
    "test_elastic_one_peer_kill_shrinks_then_grows_back_tier1",
    "tests/test_checkpoint.py::"
    "test_smoketest_corrupt_checkpoint_quarantined_not_fatal",
    "tests/test_ring_attention.py::"
    "test_ring_pipelined_bitmatches_unpipelined",
    "tests/test_serving.py::test_spec_serving_matches_plain_engine",
    "tests/test_serving.py::test_spec_serving_accepts_on_repetitive_prompts",
    "tests/test_serving.py::test_spec_serving_composes_with_prefix_and_chunking",
    "tests/test_serving.py::test_spec_serving_eos_early_stopping",
    "tests/test_serving.py::test_spec_serving_int8_matches_plain_int8_engine",
    "tests/test_serving.py::test_chunked_prefill_matches_unchunked",
    "tests/test_serving.py::test_chunked_prefill_with_prefix_caching",
    "tests/test_serving.py::test_chunked_prefill_flash_config_exact_vs_dense",
    "tests/test_serving.py::test_serve_matches_per_request_greedy_with_recycling",
    "tests/test_serving.py::test_serve_moe_config",
    "tests/test_serving.py::test_serve_flash_config_matches_its_own_greedy",
    "tests/test_serving.py::test_serve_rope_config",
    "tests/test_serving.py::test_serve_on_mesh_matches_unsharded",
    "tests/test_serving.py::test_serve_int8_cache_matches_solo_int8_decode",
    "tests/test_serving.py::test_prefix_caching_matches_full_decode",
    "tests/test_serving.py::test_eos_early_stopping_variable_lengths",
    "tests/test_serving.py::test_sampled_engine_contracts",
    # paged-engine matrix sweeps: one seeded Poisson case stays tier-1
    # (test_continuous_poisson_trace_bit_matches_solo_tier1)
    "tests/test_serving.py::test_continuous_arrival_matrix_bit_matches_solo",
    "tests/test_serving.py::test_spec_paged_occupancy_two_plus_reports_kv",
    "tests/test_paging.py::test_forward_paged_rope_per_row_positions",
    "tests/test_decode.py::test_int8_cache_speculative_still_exact",
    "tests/test_decode.py::test_int8_cache_gqa_decode",
    "tests/test_decode.py::test_int8_cache_on_mesh",
    "tests/test_burnin_model.py::test_loss_finite_unsharded",
    "tests/test_burnin_model.py::test_sharded_matches_unsharded_forward",
    "tests/test_decode.py::test_gqa_flash_prefill_close_to_dense",
    "tests/test_decode.py::test_sampling_top_k_one_is_greedy",
    "tests/test_burnin_model.py::test_gqa_forward_and_training",
    "tests/test_moe.py::test_moe_train_step_decreases_loss_on_ep_mesh",
    "tests/test_moe.py::test_tiny_capacity_drops_tokens_but_stays_finite",
    "tests/test_ulysses_attention.py::test_ulysses_matches_dense",
    "tests/test_checkpoint.py::test_resume_matches_uninterrupted_run",
    "tests/test_checkpoint.py::test_roundtrip_unsharded",
    "tests/test_decode.py::test_prefill_logits_match_forward",
    "tests/test_decode.py::test_decode_step_count_and_shapes",
    "tests/test_burnin_model.py::test_forward_shapes_unsharded",
    "tests/test_burnin_model.py::test_grad_accum_matches_full_batch",
    "tests/test_burnin_model.py::test_grad_accum_sharded_and_adamw",
    "tests/test_burnin_model.py::test_mqa_cache_replicates_heads_when_tp_does_not_divide",
    "tests/test_burnin_model.py::test_remat_is_gradient_exact",
    "tests/test_burnin_model.py::test_remat_trains_sharded",
    "tests/test_burnin_model.py::test_rope_position_sensitivity_and_training",
    "tests/test_burnin_model.py::test_sharded_train_step_decreases_loss",
    "tests/test_checkpoint.py::test_adamw_train_state_resume_bit_exact",
    "tests/test_checkpoint.py::test_smoketest_job_resume_contract",
    "tests/test_decode.py::test_compiled_decoder_matches_reference_on_mesh",
    "tests/test_decode.py::test_flash_prefill_matches_dense_prefill",
    "tests/test_decode.py::test_gqa_cache_is_smaller_and_decode_exact",
    "tests/test_decode.py::test_greedy_decode_matches_reference",
    "tests/test_decode.py::test_long_context_attn_configs_decode",
    "tests/test_decode.py::test_long_context_nontiling_prompt_policy",
    "tests/test_decode.py::test_rope_decode_matches_reference",
    "tests/test_decode.py::test_sampling_reproducible_and_varied",
    "tests/test_flash_attention.py::test_burnin_flash_train_step_decreases_loss",
    "tests/test_flash_attention.py::test_flash_gradients_match_dense",
    # full fused-backward parity sweep (block shapes × backward modes ×
    # causal × dtype, 12 interpreter-mode grad computations); one fused
    # seed stays tier-1 as test_fused_backward_tier1_seed
    "tests/test_flash_attention.py::test_fused_backward_parity_matrix",
    "tests/test_moe.py::test_moe_routes_to_multiple_experts",
    "tests/test_moe.py::test_sharded_moe_matches_unsharded",
    "tests/test_moe.py::test_single_expert_equals_dense_mlp",
    "tests/test_moe.py::test_top2_matches_handrolled_reference",
    "tests/test_moe.py::test_top2_trains_on_ep_mesh",
    "tests/test_multislice.py::test_multislice_forward_matches_unsharded",
    "tests/test_multislice.py::test_multislice_ring_attention_train",
    "tests/test_multislice.py::test_multislice_train_step_decreases_loss",
    "tests/test_multislice.py::test_smoketest_multislice_env",
    "tests/test_optimizer.py::test_adamw_matches_optax",
    "tests/test_optimizer.py::test_scheduled_adamw_trains",
    "tests/test_optimizer.py::test_sharded_adamw_trains",
    "tests/test_optimizer.py::test_sharded_adamw_trains_moe_on_ep_mesh",
    "tests/test_optimizer.py::test_unsharded_adamw_trains",
    "tests/test_pipeline.py::test_pipeline_gradients_match_reference",
    "tests/test_pipeline.py::test_pipeline_matches_reference",
    "tests/test_pipeline.py::test_pipeline_train_step_decreases_loss",
    "tests/test_pipeline.py::test_pipeline_validates_config",
    "tests/test_pipeline.py::test_pipeline_with_tp_gradients_match_reference",
    "tests/test_pipeline.py::test_pipeline_with_tp_matches_reference",
    "tests/test_pipeline.py::test_pipeline_with_tp_trains",
    "tests/test_quantize.py::test_quantized_decoder_runs_and_mostly_agrees",
    "tests/test_quantize.py::test_quantized_logits_close",
    "tests/test_quantize.py::test_tree_roundtrip_keeps_norms_exact",
    "tests/test_ring_attention.py::test_burnin_ring_matches_dense_forward",
    "tests/test_ring_attention.py::test_burnin_ring_train_step_decreases_loss",
    "tests/test_ring_attention.py::test_long_sequence_ring_memory_shape",
    "tests/test_ring_attention.py::test_ring_auto_impl_falls_back_to_dense_on_untileable_shards",
    "tests/test_ring_attention.py::test_ring_gradients_match_dense",
    "tests/test_ring_attention.py::test_ring_impl_gradients_match_dense",
    "tests/test_ring_attention.py::test_ring_impls_match_dense_at_tile_scale",
    "tests/test_ring_attention.py::test_ring_jit_under_sharded_inputs",
    "tests/test_ring_attention.py::test_ring_matches_dense",
    "tests/test_smoketest.py::test_burnin_level",
    "tests/test_ulysses_attention.py::test_burnin_ulysses_matches_dense_forward",
    "tests/test_ulysses_attention.py::test_burnin_ulysses_train_step_decreases_loss",
    "tests/test_ulysses_attention.py::test_ulysses_gradients_match_dense",
    "tests/test_ulysses_attention.py::test_ulysses_impls_match_dense_at_tile_scale",
    "tests/test_ulysses_attention.py::test_ulysses_jit_under_sharded_inputs",
})


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.nodeid.split("[")[0]
        if base in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
