# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The language-agnostic static-analysis rule engine.

Factored out of ``tfsim/lint/engine.py`` (which re-exports everything
here byte-compatibly) so ONE proven machine drives both rule packs:

* the HCL pack (``tfsim lint`` — TPU-semantic, dead-code, deprecation
  and validate-bridge rules over Terraform modules), and
* the Python pack (``graftlint`` — runtime-convention rules over the
  JAX serving stack: string-seeded RNG, no host sync in jitted loops,
  lock-ordered shared state, classified-never-silent error handling).

What lives here is everything that is NOT language-specific:

* :class:`Finding` — the one diagnostic record both packs (and
  ``tfsim validate``) render and serialise;
* :class:`Rule` + :class:`Registry` — the rule registry. Each tool owns
  a Registry instance; rule ids are unique per registry, rules carry a
  stable id, a family, a default severity and a check callable;
* per-rule severity overrides (``rule=level``, level ``off`` disables);
* suppression comments, parameterised by the tool's marker regex
  (``# tfsim:ignore rule-id`` / ``# graftlint: ignore[rule-id]``): a
  trailing comment covers its own line, a standalone comment covers the
  line below, ``*`` suppresses everything at that location;
* :meth:`Registry.run` — run every enabled rule over a tool-provided
  context, filter, sort;
* severity exit codes (2 = errors, 1 = warnings only, 0 = clean);
* the machine-readable surfaces — per-finding JSON records and SARIF
  2.1.0 documents — shared so a CI annotator parses both tools alike.

Severities order ``error > warning > info``; ``info`` never fails a
build.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Container, Iterable, Iterator, Optional

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Finding:
    severity: str   # "error" | "warning" | "info"
    where: str      # file:line
    message: str
    rule: str = ""  # stable rule id ("" for pre-lint validate callers)

    def __str__(self) -> str:
        # validate's historical rendering, unchanged: the lint CLIs format
        # findings themselves (file-first, rule-id suffix) for CI annotators
        return f"{self.severity}: {self.where}: {self.message}"

    @property
    def file(self) -> str:
        return self.where.rpartition(":")[0]

    @property
    def line(self) -> int:
        tail = self.where.rpartition(":")[2]
        return int(tail) if tail.isdigit() else 0


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str        # default; overridable per run
    family: str          # tool-defined axis ("tpu", "rng", "locking", …)
    summary: str
    check: Callable[..., Iterable]


class Registry:
    """One tool's rule catalog + the generic run loop.

    ``catalog_hint`` is appended to the unknown-rule-id error so each
    CLI points at its own ``-rules`` listing. Rule modules register
    lazily through :meth:`loader` (the HCL pack's core rules import
    ``validate`` which imports the engine back — eager loading would
    be a cycle), and :meth:`ensure_loaded` imports them exactly once.
    """

    def __init__(self, tool: str, catalog_hint: str = ""):
        self.tool = tool
        self.catalog_hint = catalog_hint
        self.rules: dict[str, Rule] = {}
        self._loaders: list[Callable[[], None]] = []
        self._loaded = False

    # ---- registration -----------------------------------------------
    def rule(self, id: str, *, severity: str, family: str, summary: str):
        """Register a rule. The check yields ``(where, message)`` pairs —
        stamped with the rule's severity — or full :class:`Finding`s when
        a single rule emits mixed severities (the validate bridge)."""
        if severity not in SEVERITIES:
            raise ValueError(f"rule {id!r}: bad default severity {severity!r}")

        def deco(fn):
            if id in self.rules:
                raise ValueError(f"duplicate rule id {id!r}")
            self.rules[id] = Rule(id=id, severity=severity, family=family,
                                  summary=summary, check=fn)
            return fn
        return deco

    def loader(self, fn: Callable[[], None]) -> Callable[[], None]:
        self._loaders.append(fn)
        return fn

    def ensure_loaded(self) -> None:
        if not self._loaded:
            self._loaded = True
            for fn in self._loaders:
                fn()

    def list(self) -> list[Rule]:
        self.ensure_loaded()
        return sorted(self.rules.values(), key=lambda r: (r.family, r.id))

    # ---- the run loop -----------------------------------------------
    def check_overrides(self, overrides: dict[str, str]) -> None:
        self.ensure_loaded()
        for rid, level in overrides.items():
            if level not in SEVERITIES and level != "off":
                raise ValueError(
                    f"-severity {rid}={level}: level must be one "
                    f"of {', '.join(SEVERITIES)} or off")
            if rid not in self.rules:
                hint = f" {self.catalog_hint}" if self.catalog_hint else ""
                raise ValueError(f"-severity {rid}: unknown rule id{hint}")

    def run(self, ctx, overrides: Optional[dict[str, str]] = None,
            suppressed: Optional[dict[tuple[str, int], set]] = None,
            ) -> list[Finding]:
        """Run every enabled rule over ``ctx`` (whatever the tool's rules
        consume). ``overrides`` maps rule id → severity (or ``"off"``);
        ``suppressed`` maps (file, line) → suppressed rule ids. Returns
        findings sorted by (file, line, rule, message)."""
        overrides = overrides or {}
        self.check_overrides(overrides)
        suppressed = suppressed or {}
        findings: list[Finding] = []
        for r in self.list():
            if overrides.get(r.id) == "off":
                continue
            for item in r.check(ctx):
                if isinstance(item, Finding):
                    f = item
                    f.rule = f.rule or r.id
                else:
                    where, message = item
                    f = Finding(r.severity, where, message, rule=r.id)
                eff = overrides.get(f.rule)
                if eff == "off":
                    continue
                if eff is not None:
                    f.severity = eff
                ids = suppressed.get((f.file, f.line), ())
                if f.rule in ids or "*" in ids:
                    continue
                findings.append(f)
        findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
        return findings


# ----------------------------------------------------------- suppression

def ignore_ids(tail: str, known: Container[str]) -> set:
    """The suppressed rule ids in an ignore comment's tail.

    The id list ends at the first token that is not a registered rule id
    (or ``*``): free prose after the list — "tfsim:ignore unused-variable
    until the v2 API lands" — must never suppress extra rules just
    because a rule id happens to be an ordinary word ("core-ref",
    "unused-local") someone typed in an explanation.
    """
    ids: set = set()
    for tok in re.split(r"[,\s]+", tail.strip()):
        if not tok:
            continue
        if tok != "*" and tok not in known:
            break
        ids.add(tok)
    return ids


def scan_suppressions(files: Iterator[tuple[str, str]],
                      marker: "re.Pattern[str]",
                      known: Container[str],
                      ) -> dict[tuple[str, int], set]:
    """(fname, line) → rule-ids suppressed there, for every ``(fname,
    text)`` pair in ``files`` whose lines carry ``marker`` comments
    (group 1 = the id-list tail).

    A trailing comment covers its own line; a standalone comment line
    covers the next line (the idiomatic "annotate the finding above it"
    placement). ``*`` suppresses every rule at that location.
    """
    out: dict[tuple[str, int], set] = {}
    for fname, text in files:
        for i, raw in enumerate(text.splitlines(), start=1):
            m = marker.search(raw)
            if not m:
                continue
            ids = ignore_ids(m.group(1), known)
            if not ids:
                continue
            target = i + 1 if raw.lstrip().startswith("#") else i
            out.setdefault((fname, target), set()).update(ids)
    return out


# ------------------------------------------------------------------ exit

def exit_code(findings: Iterable[Finding]) -> int:
    """Severity-based exit code: 2 = errors, 1 = warnings only, 0 = clean
    (info findings never fail a build)."""
    severities = {f.severity for f in findings}
    if "error" in severities:
        return 2
    if "warning" in severities:
        return 1
    return 0


# --------------------------------------------- machine-readable surfaces

def source_location(f: Finding,
                    suffixes: tuple[str, ...]) -> tuple[str, int] | None:
    """``(file, line)`` when a finding points at a real source artifact,
    else None. THE location filter for every machine-readable surface
    (JSON, SARIF): synthetic locations — pseudo-filenames with no source
    suffix and empty wheres — would make a CI annotator emit
    rejected/misplaced annotations. Line 0 (module-level findings in a
    1-based scheme) means file-only."""
    fname = f.file
    if not fname or not fname.endswith(suffixes):
        return None
    return fname, f.line


def finding_json(f: Finding, suffixes: tuple[str, ...]) -> dict:
    d = {"rule": f.rule, "severity": f.severity, "where": f.where,
         "message": f.message}
    loc = source_location(f, suffixes)
    if loc is not None:
        d["file"] = loc[0]
        if loc[1] >= 1:
            d["line"] = loc[1]
    return d


def findings_json(findings: Iterable[Finding],
                  suffixes: tuple[str, ...]) -> dict:
    """The ``-json`` document both lint CLIs print (schema shared so CI
    steps parse HCL and Python findings alike)."""
    findings = list(findings)
    counts = {s: sum(1 for f in findings if f.severity == s)
              for s in ("error", "warning", "info")}
    return {
        "format_version": "1.0",
        "clean": exit_code(findings) == 0,
        "error_count": counts["error"],
        "warning_count": counts["warning"],
        "info_count": counts["info"],
        "findings": [finding_json(f, suffixes) for f in findings],
    }


def sarif_report(findings: Iterable[Finding], rules: Iterable[Rule],
                 tool: str, suffixes: tuple[str, ...]) -> dict:
    """Minimal SARIF 2.1.0 — the format CI annotators and code-scanning
    UIs ingest natively; ``info`` maps to SARIF's ``note`` level."""
    level = {"error": "error", "warning": "warning", "info": "note"}
    results = []
    for f in findings:
        r = {"ruleId": f.rule, "level": level.get(f.severity, "warning"),
             "message": {"text": f.message}}
        loc = source_location(f, suffixes)
        if loc is not None:
            region = {"startLine": loc[1]} if loc[1] >= 1 else {}
            r["locations"] = [{"physicalLocation": {
                "artifactLocation": {"uri": loc[0]},
                **({"region": region} if region else {}),
            }}]
        results.append(r)
    return {
        "version": "2.1.0",
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "runs": [{
            "tool": {"driver": {
                "name": tool,
                "rules": [{
                    "id": r.id,
                    "shortDescription": {"text": r.summary},
                    "defaultConfiguration": {
                        "level": level.get(r.severity, "warning")},
                } for r in rules],
            }},
            "results": results,
        }],
    }
