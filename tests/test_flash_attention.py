# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Pallas flash attention: exactness vs dense, grads, burn-in integration.

Runs in pallas interpret mode on the virtual CPU mesh (the kernel's TPU
lowering shares the same trace), mirroring how tfsim stands in for terraform:
full logic coverage offline, hardware numbers from bench.py on the chip.
"""

import jax
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    init_params,
    make_train_step,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.ops import flash_attention
from nvidia_terraform_modules_tpu.ops.ring_attention import (
    dense_reference_attention,
)
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh


def _qkv(b=2, s=64, h=2, d=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_matches_dense(causal, block):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=block, block_k=block)
    ref = dense_reference_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_rectangular_blocks():
    q, k, v = _qkv(s=64)
    out = flash_attention(q, k, v, block_q=16, block_k=32)
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_gradients_match_dense():
    q, k, v = _qkv(s=32)

    def f1(q, k, v):
        return jnp.sum(jnp.square(flash_attention(q, k, v, block_q=16,
                                                  block_k=16)))

    def f2(q, k, v):
        return jnp.sum(jnp.square(dense_reference_attention(q, k, v)))

    g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_flash_bf16_close_to_f32_dense():
    q, k, v = _qkv(s=32, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v).astype(jnp.float32)
    ref = dense_reference_attention(
        *(t.astype(jnp.float32) for t in (q, k, v)))
    assert jnp.max(jnp.abs(out - ref)) < 0.05  # bf16 inputs, f32 accumulate


def test_flash_blocks_autoshrink_to_divisor():
    # S=48 with requested 32 → blocks shrink to 24; numbers unchanged
    q, k, v = _qkv(s=48)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_rejects_untileable_seq():
    # prime S with a smaller requested block leaves no divisor ≥ 8
    q, k, v = _qkv(s=97)
    with pytest.raises(ValueError, match="no block divisor"):
        flash_attention(q, k, v, block_q=32, block_k=32)


def test_fit_block_only_returns_sublane_multiples():
    """ADVICE round-1: block sizes must be 8-multiples — odd divisors like
    125 (S=250) pass CPU interpret but real-TPU pallas rejects them."""
    from nvidia_terraform_modules_tpu.ops.flash_attention import _fit_block
    assert _fit_block(192, None) == 96          # not 64? 96 divides and is 8k
    assert _fit_block(250, None) == 0           # 125 must NOT be picked
    # None default is min(1024, max(128, S/4)) — the measured v5e q-block
    # rule (1024x1024 runs S=4096 2x faster than the old 512 default)
    assert _fit_block(4096, None) == 1024
    assert _fit_block(48, 32) == 24             # 24 = 3×8, divides 48
    assert _fit_block(8, None) == 8
    assert _fit_block(4, None) == 4             # tiny interpret-only shapes
    for s in (128, 192, 256, 384, 512, 1024, 4096):
        b = _fit_block(s, None)
        assert b % 8 == 0 and s % b == 0
    # S=250 now takes the explicit pad-the-sequence error path
    q, k, v = _qkv(s=250)
    with pytest.raises(ValueError, match="pad the sequence"):
        flash_attention(q, k, v)


def test_burnin_flash_matches_dense_forward_unsharded():
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                seq_len=16, batch=4, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_f = BurnInConfig(**base, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_d)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d)
    dense = forward(params, tokens, cfg_d)
    flash = forward(params, tokens, cfg_f)
    assert jnp.max(jnp.abs(dense - flash)) < 1e-5


def test_burnin_flash_matches_dense_forward_sharded(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                seq_len=16, batch=8, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_f = BurnInConfig(**base, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_d, rules)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d, rules)
    dense = forward(params, tokens, cfg_d, rules)
    flash = forward(params, tokens, cfg_f, rules)
    assert jnp.max(jnp.abs(dense - flash)) < 1e-5


def test_burnin_flash_train_step_decreases_loss(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=8, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(4):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
