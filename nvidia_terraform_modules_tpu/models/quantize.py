# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Weight-only int8 quantization for the serve path.

Decode throughput on TPU is HBM-bound: every step re-reads the full weight
set (``models/decode.py``). Weight-only int8 halves the RESIDENT weight
footprint vs bf16 (4× vs f32) — the standard serving lever:

- **per-output-channel symmetric scales**: each matmul weight ``[in, out]``
  stores int8 values plus one f32 scale per output column — the finest
  granularity that keeps the dequant a single multiply on the matmul's
  output side;
- **store int8, compute bf16, dequant per tile in-kernel**: weights live
  as int8 and enter the decode program through :class:`QTensor`, whose
  matmuls run the pallas int8-operand kernel
  (``ops/int8_matmul.py``) — the int8→bf16 convert happens in VMEM
  inside the kernel, so int8 is what crosses HBM every decode step.
  XLA's loop-invariant-materialisation heuristic (which the previous
  dequant-then-dot design left in charge, and which is free to hoist a
  bf16 copy out of the decode scan) cannot hoist through a pallas_call;
- **norms and scales stay exact**: 1-D parameters (RMSNorm scales) are
  tiny and precision-critical — they pass through unquantized.

:class:`QTensor` duck-types the three ways the decode forward consumes a
weight — ``h @ w`` (projections/MLP), ``w[tokens]`` (embedding gather),
``x @ w.T`` (weight-tied head) — so ``models/decode.py`` runs unchanged
over int8-resident params: quantization swaps the leaves, never the
model code. ``quantize_params`` builds that tree;
``quantize_tree`` / ``dequantize_tree`` remain the storage-level API.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..parallel.sharding import ShardingRules
from .burnin import BurnInConfig
from .decode import greedy_decode


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Int8 weight + per-output-channel f32 scales, model-consumable.

    Implements exactly the operator surface ``models/decode.py`` uses on a
    weight, dispatching each to the fused int8 path:

    - ``x @ qt``: pallas int8 matmul (``ops/int8_matmul.py``) when the
      dims tile on TPU, inline-dequant ``dot_general`` otherwise;
    - ``qt[idx]``: int8 row gather, dequantized after the gather (the
      embedding lookup — B·T rows, negligible);
    - ``qt.T``: a transposed *view* (no int8 copy); its matmul contracts
      via ``transpose_rhs`` dot dimension numbers on the MXU.

    ``scale_axis`` is the axis of ``q`` the scales index (the output
    channel): 1 for ``[in, out]`` projections, 0 for the ``[vocab, d]``
    embedding (per-row scales serve both the gather and the tied head,
    where vocab IS the output channel).

    Registered as a pytree (children: q, scale) so QTensor-leaved param
    trees pass through ``jax.jit`` / ``tree.map`` like any array tree.
    Deliberately does NOT define ``__jax_array__``: jax's binary-op
    deferral then returns ``NotImplemented`` for ``array @ qtensor`` and
    python falls through to ``__rmatmul__`` here.
    """

    def __init__(self, q, scale, *, scale_axis: int, dtype,
                 transposed: bool = False):
        self.q, self.scale = q, scale
        self.scale_axis, self.transposed = scale_axis, transposed
        self.dtype = jnp.dtype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.scale_axis, self.dtype,
                                      self.transposed)

    @classmethod
    def tree_unflatten(cls, aux, children):
        scale_axis, dtype, transposed = aux
        q, scale = children
        return cls(q, scale, scale_axis=scale_axis, dtype=dtype,
                   transposed=transposed)

    @property
    def shape(self):
        s = self.q.shape
        return s[::-1] if self.transposed else s

    @property
    def T(self):  # noqa: N802 — numpy's name
        return QTensor(self.q, self.scale, scale_axis=self.scale_axis,
                       dtype=self.dtype, transposed=not self.transposed)

    def dequantize(self):
        """Dense tensor in STORAGE orientation — the one definition of
        int8→dense both the unfused serve path and any tooling share."""
        shape = (-1, 1) if self.scale_axis == 0 else (1, -1)
        return dequantize(self.q, self.scale.reshape(shape), self.dtype)

    def __getitem__(self, idx):
        if self.transposed:
            raise TypeError("gather on a transposed QTensor is not a "
                            "model access pattern")
        if self.scale_axis != 0:
            raise TypeError("QTensor gather needs per-row scales "
                            "(scale_axis=0, the embedding layout)")
        return (self.q[idx].astype(jnp.float32)
                * self.scale[idx][..., None]).astype(self.dtype)

    def __rmatmul__(self, x):
        from ..ops.int8_matmul import int8_matmul_ref

        lead, k_dim = x.shape[:-1], x.shape[-1]
        x2 = x.reshape(-1, k_dim)
        # the kernel applies scales to OUTPUT channels in its epilogue, so
        # the scale axis must be the logical output axis: storage axis 1
        # plain ([in, out] projections), storage axis 0 through a .T view
        # (the [vocab, d] embedding as tied head). Those are the only two
        # patterns the model has; anything else is a usage bug.
        if self.scale_axis != (0 if self.transposed else 1):
            raise TypeError(
                "QTensor matmul with scales on the contraction axis is not "
                "a model access pattern")
        transpose_rhs = self.transposed
        n = self.q.shape[self.scale_axis]
        scale = self.scale.reshape(1, n)
        k = self.q.shape[1 - self.scale_axis]
        if k != k_dim:
            raise ValueError(
                f"contraction mismatch: x {x.shape} @ qtensor {self.shape}")
        # the kernel path is vmap-safe via a custom_vmap rule: a batched
        # call (the serve engine's slot pool) routes to the ref
        # dequant-dot, which XLA schedules with ONE weight stream —
        # measured faster than both pallas vmap-batching (per-instance
        # tile re-fetch) and collapsing the vmap axis into M (see
        # ops/int8_matmul.with_ref_batching)
        if _kernel_ok(x2.shape[0], k, n):
            out = _kernel_mm(transpose_rhs)(x2, self.q, scale)
        else:
            out = int8_matmul_ref(x2, self.q, scale,
                                  transpose_rhs=transpose_rhs)
        return out.reshape(*lead, n)


_KERNEL_MM: dict[bool, Any] = {}


def _kernel_mm(transpose_rhs: bool):
    """Batch-collapsing kernel wrapper, one per transpose flag (cached so
    the custom_vmap identity — and its jit cache — is stable)."""
    if transpose_rhs not in _KERNEL_MM:
        import functools as _ft

        from ..ops.int8_matmul import (
            int8_matmul,
            int8_matmul_ref,
            with_ref_batching,
        )

        _KERNEL_MM[transpose_rhs] = with_ref_batching(
            _ft.partial(int8_matmul, transpose_rhs=transpose_rhs),
            _ft.partial(int8_matmul_ref, transpose_rhs=transpose_rhs))
    return _KERNEL_MM[transpose_rhs]


def _kernel_ok(m: int, k: int, n: int) -> bool:
    """Use the pallas kernel iff on real TPU, the dims tile (the lane
    axis needs 128-multiples; blocks are chosen inside the kernel), and
    the matmul is in the skinny weight-bandwidth-bound regime the kernel
    exists for (decode steps, speculative verification). At prefill
    widths (M in the hundreds) the contraction is compute-bound, XLA's
    native MXU scheduling wins, and the one-off dequant amortises over
    every row — measured on-chip: the int8 serve engine's admissions ran
    ~2x slower through the kernel. The M threshold is a PROXY for
    prefill-vs-decode: a decode batch above 64 rows would also take the
    XLA path (conservative — unmeasured territory, and at those widths
    the per-step dequant amortises 64+ ways anyway)."""
    import jax as _jax

    return (m <= 64 and _jax.devices()[0].platform == "tpu"
            and k % 128 == 0 and n % 128 == 0)


def quantize_params(params, dtype=jnp.bfloat16):
    """Params pytree → same tree with matmul weights as QTensor leaves.

    ≥2-D leaves quantize (per-output-channel scales: axis 1 for
    ``[in, out]`` projections, axis 0 — per vocab row — for the
    embedding, serving both the gather and the tied head); 1-D norm
    scales pass through untouched. The result feeds the UNMODIFIED
    decode forward: QTensor carries the quantization, the model code
    never branches.
    """

    def leaf(path, x):
        # matmul (@-consumed) weights are exactly the 2-D leaves; the MoE
        # router stays f32 (tiny, and routing decisions are
        # precision-sensitive), and 3-D expert stacks stay dense — their
        # einsum consumers don't route through QTensor (an int8 expert
        # einsum kernel is a separate lever). Classification is by the
        # leaf's EXACT key name — a substring match would silently
        # mis-quantize any future param whose name merely contains
        # "router"/"embed"
        name = getattr(path[-1], "key", None) if path else None
        if getattr(x, "ndim", 0) != 2 or name == "router":
            return x
        is_embed = name == "embed"
        axis = 0 if is_embed else -1
        q, s = quantize(x, axis=axis)
        return QTensor(q, s.reshape(-1), scale_axis=axis % x.ndim,
                       dtype=dtype)

    return jax.tree_util.tree_map_with_path(leaf, params)


def quantize(w, axis: int = -1):
    """Symmetric per-channel int8: ``(q int8, scale f32)`` with the scale
    per slice along every axis EXCEPT ``axis``'s complement — i.e. one
    scale per output channel for a ``[in, out]`` weight (axis=-1)."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=tuple(
        i for i in range(w32.ndim) if i != (axis % w32.ndim)),
        keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(q, scale, dtype=jnp.bfloat16):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _is_quantizable(path_leaf, x) -> bool:
    """Matmul weights only: ≥2-D. Norm scales (1-D) and scalars stay."""
    return getattr(x, "ndim", 0) >= 2


def quantize_tree(params) -> dict[str, Any]:
    """Params pytree → ``{"q": …, "scale": …, "kept": …}``.

    ``q``/``scale`` mirror the quantizable leaves (≥2-D); ``kept`` holds
    the untouched leaves (norm scales) at their original paths, with
    ``None`` placeholders keeping all three trees congruent.
    """
    # ONE traversal quantizes each leaf once; two cheap maps then split
    # the (q, scale) pairs into congruent trees
    pairs = jax.tree.map(
        lambda x: quantize(x) if _is_quantizable(None, x) else None,
        params)
    is_pair = lambda x: x is None or isinstance(x, tuple)  # noqa: E731
    q_tree = jax.tree.map(lambda p: None if p is None else p[0], pairs,
                          is_leaf=is_pair)
    s_tree = jax.tree.map(lambda p: None if p is None else p[1], pairs,
                          is_leaf=is_pair)
    kept = jax.tree.map(
        lambda x: None if _is_quantizable(None, x) else x, params)
    return {"q": q_tree, "scale": s_tree, "kept": kept}


def dequantize_tree(qparams, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize_tree` — runs inside the jitted consumer,
    so the stored weights stay int8 in HBM between calls."""

    def leaf(q, scale, kept):
        if q is None:
            return kept
        return dequantize(q, scale, dtype)

    return jax.tree.map(
        leaf, qparams["q"], qparams["scale"], qparams["kept"],
        is_leaf=lambda x: x is None)


def quantized_nbytes(qparams) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(qparams))


def make_quantized_decoder(cfg: BurnInConfig,
                           rules: ShardingRules | None = None,
                           n_new: int = 32, max_len: int | None = None,
                           dtype=jnp.bfloat16, fused: bool = True,
                           cache_dtype: str = "bf16"):
    """Compiled greedy decoder over int8-resident weights:
    ``decoder(qparams, prompt) → [B, n_new]`` with ``qparams`` from
    :func:`quantize_params`. The decode program is the stock
    ``greedy_decode`` — QTensor leaves route every weight matmul through
    the fused int8 kernel, so int8 bytes cross HBM on every step.

    ``fused=False`` instead dequantizes the whole tree at the top of the
    jit (the pre-kernel design) and leaves per-step weight traffic to
    XLA's loop-invariant-materialisation choice — kept so ``bench.py``
    can measure the fusion win as a number, not a claim.

    ``dtype`` is the expected compute dtype and must MATCH the one the
    QTensor leaves were built with (compute dtype is a property of the
    params, set in :func:`quantize_params`) — a mismatch errors loudly
    rather than silently computing in the params' dtype.

    ``cache_dtype="int8"`` additionally quantises the KV cache
    (``decode.init_cache``) — the full int8 serving stack: int8 weight
    bytes AND int8 cache bytes per step, the two HBM reads that bound
    decode throughput."""
    expected = jnp.dtype(dtype)
    if fused:
        def run(qparams, prompt):
            return greedy_decode(qparams, prompt, n_new, cfg, rules,
                                 max_len=max_len, cache_dtype=cache_dtype)
    else:
        def run(qparams, prompt):
            params = jax.tree.map(
                lambda x: x.dequantize() if isinstance(x, QTensor) else x,
                qparams, is_leaf=lambda x: isinstance(x, QTensor))
            return greedy_decode(params, prompt, n_new, cfg, rules,
                                 max_len=max_len, cache_dtype=cache_dtype)
    jitted = jax.jit(run)

    def decoder(qparams, prompt):
        qleaves = [leaf for leaf in jax.tree.leaves(
            qparams, is_leaf=lambda x: isinstance(x, QTensor))
            if isinstance(leaf, QTensor)]
        if not qleaves:
            raise ValueError(
                "make_quantized_decoder expects a quantize_params tree "
                "(QTensor weight leaves); got a tree with none — plain "
                "params would silently serve at full precision")
        for leaf in qleaves:
            if leaf.dtype != expected:
                raise ValueError(
                    f"decoder built for dtype {expected}, but qparams "
                    f"carry {leaf.dtype} — rebuild with "
                    f"quantize_params(params, dtype={expected})")
        return jitted(qparams, prompt)

    return decoder
