# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Static analysis for the repo's two languages, on one rule engine.

* :mod:`.core` — the language-agnostic machinery (Finding, Registry,
  severity overrides, suppressions, exit codes, JSON/SARIF), shared by
  ``tfsim lint`` (HCL) and ``graftlint`` (Python);
* :mod:`.graftlint` + :mod:`.rules_graft` — the runtime-convention
  rule pack over this package's JAX serving stack;
* :mod:`.lockgraph` — static lock-acquisition-order graph + cycles;
* :mod:`.lockwatch` — the runtime lock-order watchdog chaos tests arm.

``python -m nvidia_terraform_modules_tpu.analysis`` is the CLI.

This module imports no heavy dependencies (no jax, no numpy): the
smoketest preflight and the tfsim CLI both pull it in before any
device exists.
"""

from .core import SEVERITIES, Finding, Registry, Rule, exit_code  # noqa: F401
from .graftlint import list_rules, run_graftlint  # noqa: F401
from .pysrc import PyContext  # noqa: F401
