# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""KV-cache decode: exactness vs full re-forward, sharding, serving shape.

The cache is an optimisation, never a different model: greedy tokens from
the cached path must EQUAL greedy tokens from re-running the full burn-in
forward on the growing sequence, unsharded and on the 8-device mesh.
"""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    forward_cached,
    greedy_decode,
    init_cache,
    init_params,
    make_decoder,
)
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh


CFG = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
           seq_len=16, batch=2, dtype=jnp.float32)


def _reference_greedy(params, prompt, n_new, cfg, rules=None):
    """Greedy decode by full re-forward each step — O(T²), exact.

    The forward is jitted (one compile per sequence length at these tiny
    shapes) so sharding constraints apply under a mesh context.
    """
    fwd = jax.jit(lambda p, s: forward(p, s, cfg, rules))
    seq = prompt
    out = []
    for _ in range(n_new):
        logits = fwd(params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_prefill_logits_match_forward():
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    ref = forward(params, prompt, cfg)
    cache = init_cache(cfg, 2, 16)
    logits, cache = forward_cached(params, prompt, cache, cfg)
    assert int(cache["pos"]) == 8
    assert jnp.max(jnp.abs(logits - ref)) < 1e-5


def test_greedy_decode_matches_reference():
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    ref = _reference_greedy(params, prompt, 10, cfg)
    got = greedy_decode(params, prompt, 10, cfg)
    assert jnp.array_equal(ref, got), (ref, got)


def test_compiled_decoder_matches_reference_on_mesh(jax8):
    """Sharded cached decode vs full re-forward UNDER THE SAME RULES —
    comparing same-layout numerics keeps the test free of XLA
    reduction-order coincidences across layouts."""
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, cfg.vocab)
    ref = _reference_greedy(params, prompt, 8, cfg, rules)
    decoder = make_decoder(cfg, rules, n_new=8)
    got = decoder(params, prompt)
    assert jnp.array_equal(jax.device_get(ref), jax.device_get(got))


def test_decode_step_count_and_shapes():
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 4), 0, cfg.vocab)
    toks = greedy_decode(params, prompt, 5, cfg)
    assert toks.shape == (3, 5)
    assert toks.dtype in (jnp.int32, jnp.int64)


def test_decode_rejects_overflow():
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    with pytest.raises(ValueError, match="exceeds"):
        greedy_decode(params, prompt, 16, cfg, max_len=16)


@pytest.mark.slow
def test_moe_greedy_decode_matches_reference():
    """MoE serving exactness: with a training capacity factor that avoids
    drops (>= n_experts), cached MoE decode equals the full re-forward
    token by token — routing is per-token, and the serve path's
    drop-free capacity makes it independent of sequence length."""
    cfg = BurnInConfig(**{**CFG, "n_experts": 4, "capacity_factor": 4.0})
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    toks = greedy_decode(params, prompt, 6, cfg)
    seq = prompt
    for step in range(6):
        logits = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        assert np.array_equal(np.asarray(nxt), np.asarray(toks[:, step])), \
            f"step {step}"
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)


@pytest.mark.slow
def test_moe_top2_decode_runs_and_matches():
    cfg = BurnInConfig(**{**CFG, "n_experts": 4, "router_top_k": 2,
                          "capacity_factor": 8.0})
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0, cfg.vocab)
    toks = greedy_decode(params, prompt, 4, cfg)
    logits = forward(params, prompt, cfg)
    first = jnp.argmax(logits[:, -1], axis=-1)
    assert np.array_equal(np.asarray(first), np.asarray(toks[:, 0]))


@pytest.mark.slow
def test_moe_decode_on_ep_mesh_matches_unsharded(jax8):
    from nvidia_terraform_modules_tpu.parallel import (
        build_mesh,
        make_rules,
        plan_mesh,
    )

    cfg = BurnInConfig(**{**CFG, "n_experts": 2, "capacity_factor": 2.0,
                          "batch": 4})
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, cfg.vocab)
    want = greedy_decode(params, prompt, 5, cfg)
    rules = make_rules(build_mesh(plan_mesh(8, ep=2, tp=2)))
    from nvidia_terraform_modules_tpu.models.burnin import shard_params

    sharded = shard_params(params, rules)
    got = greedy_decode(sharded, prompt, 5, cfg, rules)
    assert np.array_equal(np.asarray(want), np.asarray(got))


def test_long_context_attn_configs_decode():
    """A flash/ring-trained config serves as-is: decode ignores the
    training attention layout (same weights, own cached attention)."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 64)
    outs = []
    for attn in ("dense", "flash", "ring"):
        cfg = BurnInConfig(**{**CFG, "attn": attn})
        params = init_params(jax.random.PRNGKey(0), cfg)
        outs.append(greedy_decode(params, prompt, 6, cfg))
    assert jnp.array_equal(outs[0], outs[1])
    assert jnp.array_equal(outs[0], outs[2])


def test_flash_prefill_matches_dense_prefill():
    """prefill_impl='flash' is a kernel swap, not a different model."""
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                cfg.vocab)
    dense_logits, dense_cache = forward_cached(
        params, prompt, init_cache(cfg, 2, 80), cfg)
    flash_logits, flash_cache = forward_cached(
        params, prompt, init_cache(cfg, 2, 80), cfg, prefill_impl="flash")
    assert jnp.max(jnp.abs(dense_logits - flash_logits)) < 2e-5
    # layer-0 K comes straight from the prompt (identical); deeper layers
    # inherit the attention impl's float noise through the residual stream
    assert jnp.array_equal(dense_cache["k"][0], flash_cache["k"][0])
    for a, b in zip(dense_cache["k"][1:], flash_cache["k"][1:]):
        assert jnp.max(jnp.abs(a - b)) < 2e-5


def test_sampling_top_k_one_is_greedy():
    from nvidia_terraform_modules_tpu.models import sample_decode

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    greedy = greedy_decode(params, prompt, 8, cfg)
    topk1 = sample_decode(params, prompt, 8, cfg, jax.random.PRNGKey(7),
                          top_k=1, temperature=3.0)
    assert jnp.array_equal(greedy, topk1)


def test_sampling_reproducible_and_varied():
    from nvidia_terraform_modules_tpu.models import sample_decode

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    a = sample_decode(params, prompt, 16, cfg, jax.random.PRNGKey(3),
                      temperature=2.0)
    b = sample_decode(params, prompt, 16, cfg, jax.random.PRNGKey(3),
                      temperature=2.0)
    c = sample_decode(params, prompt, 16, cfg, jax.random.PRNGKey(4),
                      temperature=2.0)
    assert jnp.array_equal(a, b)            # same key → same tokens
    assert not jnp.array_equal(a, c)        # different key → different draw
    assert a.shape == (2, 16)
    with pytest.raises(ValueError, match="top_k"):
        sample_decode(params, prompt, 4, cfg, jax.random.PRNGKey(0),
                      top_k=0)


def test_cache_is_tp_sharded_on_mesh(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=1))
    rules = make_rules(mesh)
    cfg = BurnInConfig(**CFG)
    cache = init_cache(cfg, 4, 16, rules)
    spec = cache["k"][0].sharding.spec
    assert spec[2] == "tp"     # heads sharded over tp


def test_long_context_nontiling_prompt_policy():
    """Flash-config prompts that cannot tile: short ones fall back to the
    memory-safe dense path (t=1 can never use flash anyway), LARGE ones
    error loudly instead of materialising a [T, S_max] score matrix."""
    from nvidia_terraform_modules_tpu.models.decode import (
        _select_prefill_impl,
    )

    cfg = BurnInConfig(**{**CFG, "attn": "flash"})
    params = init_params(jax.random.PRNGKey(0), cfg)
    # short non-tiling prompt (100 = 2²·5²): silent dense fallback, runs
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 100), 0,
                                cfg.vocab)
    toks = greedy_decode(params, prompt, 4, cfg, max_len=128)
    assert toks.shape == (2, 4)
    # single-token prompts must always be servable
    one = jax.random.randint(jax.random.PRNGKey(1), (2, 1), 0, cfg.vocab)
    assert greedy_decode(params, one, 4, cfg).shape == (2, 4)
    # large non-tiling prompt (513 = 3³·19): loud error, not an OOM
    with pytest.raises(ValueError, match="pad the prompt"):
        _select_prefill_impl(cfg, 513, "auto")
    # explicit dense is always allowed — the operator owns the memory call
    assert _select_prefill_impl(cfg, 513, "dense") == "dense"


def test_gqa_cache_is_smaller_and_decode_exact():
    """GQA: the cache stores only KV heads (n_heads/kv_heads smaller), and
    greedy decode still EQUALS the full re-forward reference."""
    cfg = BurnInConfig(**{**CFG, "n_heads": 4, "n_kv_heads": 2})
    params = init_params(jax.random.PRNGKey(0), cfg)
    cache = init_cache(cfg, 2, 16)
    assert cache["k"][0].shape == (2, 16, 2, cfg.head_dim)   # KV heads only
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    ref = _reference_greedy(params, prompt, 8, cfg)
    got = greedy_decode(params, prompt, 8, cfg)
    assert jnp.array_equal(ref, got)


def test_gqa_flash_prefill_close_to_dense():
    cfg = BurnInConfig(**{**CFG, "n_heads": 4, "n_kv_heads": 1,
                          "attn": "flash"})
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                cfg.vocab)
    d_logits, _ = forward_cached(params, prompt, init_cache(cfg, 2, 80),
                                 cfg, prefill_impl="dense")
    f_logits, _ = forward_cached(params, prompt, init_cache(cfg, 2, 80),
                                 cfg, prefill_impl="flash")
    assert jnp.max(jnp.abs(d_logits - f_logits)) < 2e-5


def test_rope_decode_matches_reference():
    """Cached decode with RoPE (K rotated before the cache write) still
    EQUALS the full re-forward reference, GQA included."""
    cfg = BurnInConfig(**{**CFG, "rope": True, "n_kv_heads": 2})
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    ref = _reference_greedy(params, prompt, 10, cfg)
    got = greedy_decode(params, prompt, 10, cfg)
    assert jnp.array_equal(ref, got), (ref, got)


@pytest.mark.slow
def test_moe_chunked_prefill_matches_unchunked():
    """Prompts longer than the routing chunk take the scan path; with
    drop-free capacity, chunking must change memory only, never tokens."""
    import nvidia_terraform_modules_tpu.models.decode as dec

    cfg = BurnInConfig(**{**CFG, "n_experts": 4, "capacity_factor": 4.0,
                          "seq_len": 256})
    params = init_params(jax.random.PRNGKey(0), cfg)
    # 150 tokens: crosses one chunk boundary AND exercises the padding
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 150), 0,
                                cfg.vocab)
    assert prompt.shape[1] > dec._MOE_PREFILL_CHUNK
    cache = init_cache(cfg, 2, 160)
    logits, _ = forward_cached(params, prompt, cache, cfg)
    ref = forward(params, prompt, cfg)
    assert float(jnp.max(jnp.abs(logits - ref))) < 1e-4


def test_sampling_top_p_tiny_keeps_argmax_only():
    """top_p small enough keeps exactly the argmax token (the first
    sorted token always survives nucleus filtering), so sampling becomes
    greedy — the top-p analogue of the top_k=1 contract."""
    from nvidia_terraform_modules_tpu.models import sample_decode

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    greedy = greedy_decode(params, prompt, 8, cfg)
    nucleus = sample_decode(params, prompt, 8, cfg, jax.random.PRNGKey(7),
                            top_p=1e-6, temperature=5.0)
    assert jnp.array_equal(greedy, nucleus)


def test_sampling_top_p_one_is_plain_sampling():
    from nvidia_terraform_modules_tpu.models import sample_decode

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab)
    a = sample_decode(params, prompt, 8, cfg, jax.random.PRNGKey(9))
    b = sample_decode(params, prompt, 8, cfg, jax.random.PRNGKey(9),
                      top_p=1.0)
    assert jnp.array_equal(a, b)


def test_sampling_top_p_validation():
    from nvidia_terraform_modules_tpu.models import sample_decode

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="top_p"):
        sample_decode(params, prompt, 4, cfg, jax.random.PRNGKey(0),
                      top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        sample_decode(params, prompt, 4, cfg, jax.random.PRNGKey(0),
                      top_p=1.5)


# ---------------------------------------------------------------- int8 cache


def test_quantize_kv_roundtrip_bound():
    """Per-vector symmetric int8: |dequant - x| <= scale (one rounding
    step), scale = amax/127 per cached vector."""
    from nvidia_terraform_modules_tpu.models import quantize_kv

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 3, 16), jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 5, 3)
    deq = q.astype(jnp.float32) * s[..., None]
    amax = jnp.max(jnp.abs(x), axis=-1)
    assert jnp.allclose(s, amax / 127.0)
    assert float(jnp.max(jnp.abs(deq - x) - s[..., None])) <= 1e-6


def test_int8_cache_structure_and_dtypes():
    from nvidia_terraform_modules_tpu.models.decode import cache_rows

    # GQA config on purpose: the scale sidecar is per KV head (the cache
    # only stores KV heads), not per query head
    cfg = BurnInConfig(**{**CFG, "n_kv_heads": 2})
    cache = init_cache(cfg, 2, 24, cache_dtype="int8")
    assert cache["k"][0].dtype == jnp.int8
    # int8 buffers round rows up to the decode kernel's 256-row grain
    # (cache_rows); the extra rows sit above pos, masked forever
    rows = cache_rows(24, "int8")
    assert rows == 256
    assert cache["k"][0].shape == (2, rows, cfg.kv_heads, cfg.head_dim)
    assert cache["v_scale"][0].shape == (2, rows, cfg.kv_heads)
    with pytest.raises(ValueError, match="cache_dtype"):
        init_cache(cfg, 2, 24, cache_dtype="fp8")


def test_int8_cache_decode_tracks_exact_path():
    """The int8 cache is lossy but must stay CLOSE: same first token
    (prefill logits dominated by full-precision math) and high token
    agreement with the bf16-cache decode on the same weights. All
    deterministic: fixed seeds, CPU f32."""
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    exact = greedy_decode(params, prompt, 12, cfg)
    quant = greedy_decode(params, prompt, 12, cfg, cache_dtype="int8")
    assert quant.shape == exact.shape
    agreement = float(jnp.mean((exact == quant).astype(jnp.float32)))
    assert jnp.array_equal(exact[:, 0], quant[:, 0])
    assert agreement >= 0.75, f"int8 cache agreement {agreement}"


def test_int8_cache_prefill_is_full_precision():
    """The pos-0 prefill must NOT read quantised rows: its logits equal
    the bf16-cache prefill's bit for bit (only decode STEPS pay the
    quantisation noise)."""
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    exact_logits, _ = forward_cached(
        params, prompt, init_cache(cfg, 2, 16), cfg)
    quant_logits, qcache = forward_cached(
        params, prompt, init_cache(cfg, 2, 16, cache_dtype="int8"), cfg)
    assert jnp.array_equal(exact_logits, quant_logits)
    # ...while the cache rows themselves ARE quantised for later steps
    assert qcache["k"][0].dtype == jnp.int8


def test_int8_cache_gqa_decode():
    """GQA + int8 cache: grouped-query contraction over dequantised
    buffers — runs, tracks the exact path, sidecar shaped per KV head."""
    cfg = BurnInConfig(**{**CFG, "n_kv_heads": 2})
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    exact = greedy_decode(params, prompt, 12, cfg)
    quant = greedy_decode(params, prompt, 12, cfg, cache_dtype="int8")
    assert jnp.array_equal(exact[:, 0], quant[:, 0])
    agreement = float(jnp.mean((exact == quant).astype(jnp.float32)))
    assert agreement >= 0.75, f"GQA int8 cache agreement {agreement}"


def test_int8_cache_speculative_still_exact():
    """Speculative decoding's t>1 verification forwards are mid-stream
    ("cached"), not prefills — with the default bf16 cache the exactness
    guarantee must survive the new prefill routing."""
    from nvidia_terraform_modules_tpu.models import (
        speculative_greedy_decode,
    )

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    span = jax.random.randint(jax.random.PRNGKey(3), (1, 4), 0, cfg.vocab)
    prompt = jnp.tile(span, (1, 3))
    toks, _steps = speculative_greedy_decode(params, prompt, 10, cfg, k=3)
    ref = greedy_decode(params, prompt, 10, cfg)
    assert jnp.array_equal(toks, ref)


def test_int8_cache_on_mesh(jax8):
    """int8 cache + tp-sharded heads: the scale sidecar must shard with
    the cache and the compiled decoder must run on the mesh."""
    mesh = build_mesh(plan_mesh(8, tp=2, sp=1))
    rules = make_rules(mesh)
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, cfg.vocab)
    dec = make_decoder(cfg, rules, n_new=6, max_len=16, cache_dtype="int8")
    toks = dec(params, prompt)
    assert toks.shape == (4, 6)
    ref = make_decoder(cfg, None, n_new=6, max_len=16, cache_dtype="int8")(
        jax.device_get(params), jax.device_get(prompt))
    assert jnp.array_equal(jax.device_get(toks), ref)


def test_int8_cache_full_int8_stack():
    """int8 weights (fused kernel) + int8 cache compose."""
    from nvidia_terraform_modules_tpu.models import (
        make_quantized_decoder,
        quantize_params,
    )

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params, dtype=cfg.dtype)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
    dec = make_quantized_decoder(cfg, n_new=6, max_len=16, dtype=cfg.dtype,
                                 cache_dtype="int8")
    toks = dec(qparams, prompt)
    assert toks.shape == (2, 6)
