# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Workspaces: named state environments per module dir (terraform-shaped).

Terraform workspaces let one configuration hold several independent states
(``terraform workspace new staging`` → state moves to
``terraform.tfstate.d/staging/``; the selection lives in
``.terraform/environment``). The reference's README leans on exactly this
"one module, many deployments" pattern via separate tfvars files
(``/root/reference/README.md:43-79``); workspaces are the CLI-native face of
it, and ``terraform.workspace`` is referenceable from HCL (e.g. per-env
cluster names).

tfsim mirrors the on-disk contract, adapted to its explicit-state model:

- the selection lives in ``<dir>/.tfsim/environment`` (analogue of
  ``.terraform/environment`` — also outside version control);
- per-workspace state: ``<dir>/terraform.tfstate.json`` for ``default``,
  ``<dir>/terraform.tfstate.d/<name>/terraform.tfstate.json`` otherwise
  (terraform's exact layout, with tfsim's ``.json`` statefile suffix);
- state-path resolution is OPT-IN: ``plan``/``apply``/``output`` only derive
  a state path from the workspace when the module dir has an environment
  file (i.e. a workspace verb has been used there) and no explicit
  ``-state`` was passed — so existing explicit-state workflows and CI runs
  are untouched.
"""

from __future__ import annotations

import os

DEFAULT = "default"
_STATE_FILE = "terraform.tfstate.json"


class WorkspaceError(ValueError):
    pass


def _env_file(module_dir: str) -> str:
    return os.path.join(module_dir, ".tfsim", "environment")


def _state_dir(module_dir: str) -> str:
    return os.path.join(module_dir, "terraform.tfstate.d")


def workspaces_enabled(module_dir: str) -> bool:
    """True once any workspace verb has run in this module dir."""
    return os.path.exists(_env_file(module_dir))


def current_workspace(module_dir: str) -> str:
    try:
        with open(_env_file(module_dir)) as fh:
            name = fh.read().strip()
        return name or DEFAULT
    except OSError:
        return DEFAULT


def list_workspaces(module_dir: str) -> list[str]:
    """All known workspaces: ``default`` plus every state subdirectory."""
    names = {DEFAULT}
    d = _state_dir(module_dir)
    if os.path.isdir(d):
        names.update(n for n in os.listdir(d)
                     if os.path.isdir(os.path.join(d, n)))
    return sorted(names)


def workspace_state_path(module_dir: str, name: str | None = None) -> str:
    """The statefile a workspace owns (terraform.tfstate.d layout)."""
    name = name or current_workspace(module_dir)
    if name == DEFAULT:
        return os.path.join(module_dir, _STATE_FILE)
    return os.path.join(_state_dir(module_dir), name, _STATE_FILE)


def backend_state_path(module_dir: str, backend,
                       workspace: str | None = None) -> str:
    """Statefile for a ``terraform { backend "…" }`` declaration.

    The reference recommends remote state for shared use
    (``/root/reference/README.md:89-91``) but never configures it; tfsim
    makes the workflow representable offline. The ``gcs`` backend maps
    the bucket to a local directory tree — ``$TFSIM_GCS_ROOT`` (so two
    checkouts can genuinely share one "bucket", the multi-operator
    story) or ``<dir>/.terraform/gcs-sim`` by default — laid out the way
    the real backend lays out objects: ``<prefix>/<workspace>.tfstate``.
    The ``local`` backend honours its ``path`` attribute. Anything else
    is declared-but-unsimulated: a clean error says to pass ``-state``.
    """
    name = workspace or (current_workspace(module_dir)
                         if workspaces_enabled(module_dir) else DEFAULT)
    if backend.type == "gcs":
        bucket = backend.config.get("bucket")
        if not isinstance(bucket, str) or not bucket:
            raise WorkspaceError(
                'backend "gcs" requires a literal `bucket` attribute')
        root = os.environ.get("TFSIM_GCS_ROOT") or os.path.join(
            module_dir, ".terraform", "gcs-sim")
        prefix = str(backend.config.get("prefix", "")).strip("/")
        parts = [root, bucket] + ([prefix] if prefix else [])
        return os.path.join(*parts, f"{name}.tfstate.json")
    if backend.type == "local":
        if name != DEFAULT:
            return os.path.join(_state_dir(module_dir), name, _STATE_FILE)
        return os.path.join(module_dir,
                            str(backend.config.get("path",
                                                   "terraform.tfstate")))
    raise WorkspaceError(
        f'backend "{backend.type}" is not simulated by tfsim (gcs and '
        f"local are) — pass -state to choose the statefile explicitly")


def resolve_state_path(module_dir: str, explicit: str | None,
                       workspace: str | None = None,
                       backend=None) -> str | None:
    """State path for a plan/apply/output invocation.

    Explicit ``-state`` always wins; then a declared ``backend`` block;
    then the workspace's statefile — but only when workspaces are enabled
    for the dir (opt-in, see module docstring). Returns None to mean "no
    state" (the legacy behaviour).
    """
    if explicit:
        return explicit
    if backend is not None:
        return backend_state_path(module_dir, backend, workspace)
    if workspace or workspaces_enabled(module_dir):
        return workspace_state_path(module_dir, workspace)
    return None


def _select(module_dir: str, name: str) -> None:
    env = _env_file(module_dir)
    os.makedirs(os.path.dirname(env), exist_ok=True)
    with open(env, "w") as fh:
        fh.write(name + "\n")


def new_workspace(module_dir: str, name: str) -> None:
    _check_name(name)
    if name in list_workspaces(module_dir):
        raise WorkspaceError(f'workspace "{name}" already exists')
    if name != DEFAULT:
        os.makedirs(os.path.join(_state_dir(module_dir), name), exist_ok=True)
    _select(module_dir, name)


def select_workspace(module_dir: str, name: str) -> None:
    if name not in list_workspaces(module_dir):
        raise WorkspaceError(
            f'workspace "{name}" does not exist — create it with '
            f'`workspace new {name}`')
    _select(module_dir, name)


def delete_workspace(module_dir: str, name: str, force: bool = False) -> None:
    if name == DEFAULT:
        raise WorkspaceError('the "default" workspace cannot be deleted')
    if name == current_workspace(module_dir):
        raise WorkspaceError(
            f'workspace "{name}" is the current workspace — select another '
            f'one first')
    if name not in list_workspaces(module_dir):
        raise WorkspaceError(f'workspace "{name}" does not exist')
    state = workspace_state_path(module_dir, name)
    if os.path.exists(state) and not force:
        # terraform refuses to delete a non-empty workspace without -force
        raise WorkspaceError(
            f'workspace "{name}" still has state ({state}); re-run with '
            f'-force to discard it')
    try:
        if os.path.exists(state):
            os.remove(state)
        d = os.path.join(_state_dir(module_dir), name)
        if os.path.isdir(d):
            os.rmdir(d)
    except OSError as ex:
        # e.g. stray files next to the statefile: keep the CLI's
        # "Error: …" exit-1 contract instead of a traceback
        raise WorkspaceError(
            f'could not remove workspace "{name}": {ex}')


def _check_name(name: str) -> None:
    if not name or not all(c.isalnum() or c in "-_" for c in name):
        raise WorkspaceError(
            f"invalid workspace name {name!r}: use letters, digits, - and _")
