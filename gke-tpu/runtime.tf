# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# TPU runtime layer (L4): the TPU-native replacement for the GPU Operator.
#
# On GKE TPU node pools the driver-equivalent (libtpu) and the TPU device
# plugin ship with the node image — there is no NVIDIA-style driver install
# to orchestrate. What remains, and what this layer installs from the in-repo
# chart charts/tpu-runtime, is the operational envelope the GPU Operator
# provided on the GPU side (/root/reference/gke/main.tf:156-213):
#
#   - a node health-probe DaemonSet on every TPU host (device enumeration via
#     libtpu, exported as node conditions for the autoscaler / alerting);
#   - a priority class + namespace quota so runtime pods schedule ahead of
#     workloads (mirroring the reference's system-priority quota);
#   - labels/tolerations wiring for google.com/tpu resources.
#
# The chart owns its namespace objects, and the release depends on the slice
# pools — so destroy unwinds release → pools → cluster without the
# reference's manual `state rm` step (survey §3.4).

# The namespace is a first-class resource (not helm create_namespace) so the
# smoke-test resources can live in it even when the runtime layer is
# disabled; it depends on the slice pools to keep destroy ordering clean.
resource "kubernetes_namespace_v1" "tpu_runtime" {
  count = local.tpu_enabled && (var.tpu_runtime.enabled || var.smoketest.enabled) ? 1 : 0

  metadata {
    name = var.tpu_runtime.namespace

    labels = {
      "app.kubernetes.io/managed-by" = "terraform"
      "app.kubernetes.io/part-of"    = "tpu-terraform-modules"
    }
  }

  depends_on = [google_container_node_pool.tpu_slice]
}

resource "helm_release" "tpu_runtime" {
  count = local.tpu_enabled && var.tpu_runtime.enabled ? 1 : 0

  name      = "tpu-runtime"
  chart     = "${path.module}/../charts/tpu-runtime"
  namespace = kubernetes_namespace_v1.tpu_runtime[0].metadata[0].name

  atomic          = true
  cleanup_on_fail = true
  replace         = true
  timeout         = 900

  # yamlencode'd values block — immune to Helm's --set comma parsing, which
  # would truncate a multi-generation selector list passed via `set`
  values = [
    yamlencode({
      image = {
        probe = var.tpu_runtime.image
      }
      tpu = {
        nodeSelectors = join(",", distinct([for s in local.tpu_slice : s.node_selector]))
      }
      probe = {
        metrics = {
          podMonitoring = var.tpu_runtime.pod_monitoring
        }
      }
    })
  ]

  depends_on = [google_container_node_pool.tpu_slice]
}
