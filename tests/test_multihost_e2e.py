# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""True multi-process e2e: the smoke-test payloads under jax.distributed.

Spawns two processes (4 virtual CPU devices each) that form one 8-device
global mesh over a localhost coordinator — the exact choreography of the
gke-tpu indexed Job across slice hosts, minus the TPUs.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BOOTSTRAP = (
    "import jax, runpy;"
    "jax.config.update('jax_platforms', 'cpu');"
    "runpy.run_path(r'{script}', run_name='__main__')"
)


def _spawn(idx: int, script: str, extra_env: dict, port: int,
           devices_per_proc: int = 4):
    env = dict(os.environ)
    env.update(
        XLA_FLAGS=f"--xla_force_host_platform_device_count={devices_per_proc}",
        JAX_PLATFORMS="cpu",
        TPU_SMOKETEST_HOSTS="2",
        JOB_COMPLETION_INDEX=str(idx),
        TPU_SMOKETEST_COORDINATOR=f"localhost:{port}",
        TPU_SMOKETEST_EXPECTED_DEVICES="8",
        TPU_SMOKETEST_INIT_TIMEOUT="60",
    )
    env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-c", BOOTSTRAP.format(script=script)],
        env=env, cwd=ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _run_pair(script: str, extra_env: dict, port: int, _attempts: int = 3):
    # init-path failures are no longer this harness's problem: the REAL
    # policy in parallel/multihost.py (bounded TCP pre-flight with capped
    # backoff + jitter, classified DistributedInitError) covers a world
    # that never assembles — see test_multihost.py. What remains here is
    # the one failure the process cannot handle itself: older jaxlib's
    # gloo TCP transport has a rare connect race that aborts a process
    # with "op.preamble.length <= op.nbytes" MID-RUN; it is a transport
    # flake, not a smoketest verdict, so the pair is retried a
    # bounded number of times. A killed attempt may have already written
    # checkpoints the next attempt would silently resume from — snapshot
    # the checkpoint dir (when the test uses one) and restore it before a
    # retry so every attempt sees the pre-pair state.
    ckpt = extra_env.get("TPU_SMOKETEST_CHECKPOINT_DIR")
    snap = None
    if _attempts > 1 and ckpt:
        snap = tempfile.mkdtemp(prefix="e2e_ckpt_snap_")
        if os.path.isdir(ckpt):
            shutil.copytree(ckpt, os.path.join(snap, "d"))
    try:
        procs = [_spawn(i, script, extra_env, port) for i in range(2)]
        results = []
        for p in procs:
            out, err = p.communicate(timeout=240)
            results.append((p.returncode, out, err))
        if _attempts > 1 and any(
                rc != 0 and "op.preamble.length" in err
                for rc, _, err in results):
            if ckpt:
                shutil.rmtree(ckpt, ignore_errors=True)
                if os.path.isdir(os.path.join(snap, "d")):
                    shutil.copytree(os.path.join(snap, "d"), ckpt)
            return _run_pair(script, extra_env, port, _attempts - 1)
        return results
    finally:
        if snap:
            shutil.rmtree(snap, ignore_errors=True)


def _verdict(out: str) -> dict:
    return json.loads([l for l in out.splitlines() if l.startswith("{")][-1])


@pytest.mark.slow
def test_standalone_script_two_hosts():
    script = os.path.join(ROOT, "gke-tpu", "scripts", "tpu_smoketest.py")
    results = _run_pair(script, {"TPU_SMOKETEST_LEVEL": "probes"}, port=8491)
    for rc, out, err in results:
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        verdict = json.loads(line)
        assert verdict["ok"] is True
        assert verdict["devices"] == 8
        assert verdict["num_processes"] == 2
        assert verdict["psum_ok"] and verdict["ring_ok"] and verdict["all_gather_ok"]


@pytest.mark.slow
def test_standalone_script_two_slices_four_processes():
    """The full multi-slice Job contract (smoketest.tf multislice=true),
    driven end-to-end on CPU: 2 slices × 2 hosts, one process per host with
    2 virtual devices, joined into ONE jax.distributed world over a shared
    coordinator. Process ids come from JOB_COMPLETION_INDEX +
    TPU_SMOKETEST_PROCESS_BASE exactly as the Job env wires them; every
    pod's JSON must report the cross-slice psum (dcn_psum_ok)."""
    script = os.path.join(ROOT, "gke-tpu", "scripts", "tpu_smoketest.py")
    port = 8493
    procs = []
    for slice_id, base in ((0, 0), (1, 2)):
        for idx in (0, 1):
            procs.append(_spawn(
                idx, script,
                {
                    "TPU_SMOKETEST_LEVEL": "probes",
                    "TPU_SMOKETEST_HOSTS": "4",
                    "TPU_SMOKETEST_SLICES": "2",
                    "TPU_SMOKETEST_PROCESS_BASE": str(base),
                    # MEGASCALE_* is libtpu-only; harmless on CPU but set to
                    # mirror the Job env exactly
                    "MEGASCALE_NUM_SLICES": "2",
                    "MEGASCALE_SLICE_ID": str(slice_id),
                    "MEGASCALE_COORDINATOR_ADDRESS": f"localhost:{port}",
                },
                port=port, devices_per_proc=2))
    results = [(p.communicate(timeout=300), p.returncode) for p in procs]
    for (out, err), rc in results:
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        verdict = _verdict(out)
        assert verdict["ok"] is True
        assert verdict["devices"] == 8
        assert verdict["num_processes"] == 4
        assert verdict["slices"] == 2
        assert verdict["dcn_psum_ok"] is True
        assert verdict["psum_ok"] and verdict["ring_ok"]
        assert verdict["ring_gibps"] > 0
        assert verdict["all_gather_gibps"] > 0
    # the four processes collectively covered ids 0..3
    ids = sorted(_verdict(out)["process_id"] for (out, _), _ in results)
    assert ids == [0, 1, 2, 3]


@pytest.mark.slow
def test_standalone_script_bad_slice_config_fails():
    """n % slices != 0 must fail the contract, not silently skip DCN
    validation (ADVICE round-1, low)."""
    script = os.path.join(ROOT, "gke-tpu", "scripts", "tpu_smoketest.py")
    results = _run_pair(script, {
        "TPU_SMOKETEST_LEVEL": "psum",
        "TPU_SMOKETEST_SLICES": "3",   # 8 devices % 3 != 0
    }, port=8494)
    for rc, out, err in results:
        assert rc == 1, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        verdict = _verdict(out)
        assert verdict["ok"] is False
        assert verdict["dcn_psum_ok"] is False
        assert "slices_error" in verdict


def _pkg_runner(tmp_path):
    runner = tmp_path / "run_pkg.py"
    runner.write_text(
        "import sys; sys.path.insert(0, r'%s')\n"
        "from nvidia_terraform_modules_tpu.smoketest.__main__ import main\n"
        "sys.exit(main())\n" % ROOT
    )
    return str(runner)


@pytest.mark.slow
def test_package_runner_two_hosts(tmp_path):
    # drive the installable package runner the same way
    runner = _pkg_runner(tmp_path)
    results = _run_pair(str(runner), {"TPU_SMOKETEST_LEVEL": "psum"}, port=8492)
    for rc, out, err in results:
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        verdict = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1])
        assert verdict["ok"] is True
        assert verdict["devices"] == 8
        assert verdict["psum_participants"] == 8


@pytest.mark.slow
def test_package_runner_full_level_two_hosts(tmp_path):
    """Level full on the PACKAGE runner across 2 processes: the serving
    engine's host-side admission/recycling loop must run identically on
    every controller (no per-step sync without eos) while the pool
    shards over the global mesh — the multi-controller contract the
    in-cluster Job relies on. Also pins the ep/pp fabric keys the
    bundled-script full test covers, for the package path."""
    runner = _pkg_runner(tmp_path)
    results = _run_pair(str(runner), {"TPU_SMOKETEST_LEVEL": "full"},
                        port=8499)
    for rc, out, err in results:
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        verdict = _verdict(out)
        assert verdict["ok"] is True
        assert verdict["serving_ok"] is True
        assert verdict["serving_requests"] == 2 * verdict["serving_slots"]
        assert verdict["all_to_all_ep_ok"] is True
        assert verdict["moe_ok"] is True
        assert verdict["pipeline_ok"] is True
        assert verdict["burnin_ok"] is True and verdict["decode_ok"] is True


@pytest.mark.slow
def test_standalone_script_burnin_resume(tmp_path):
    """Spot-preemption contract for the bundled payload: a checkpoint left
    by a preempted attempt resumes the global step; success clears it so a
    later fresh Job starts at 0; a corrupt file fails via JSON, not a
    traceback."""
    import numpy as np

    script = os.path.join(ROOT, "gke-tpu", "scripts", "tpu_smoketest.py")
    env = dict(os.environ)
    env.update(
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_PLATFORMS="cpu",
        TPU_SMOKETEST_LEVEL="burnin",
        TPU_SMOKETEST_CHECKPOINT_DIR=str(tmp_path),
    )
    ckpt = tmp_path / "burnin_p0.npz"

    def attempt(expect_rc=0):
        p = subprocess.run(
            [sys.executable, "-c", BOOTSTRAP.format(script=script)],
            env=env, cwd=ROOT, capture_output=True, text=True, timeout=240)
        assert p.returncode == expect_rc, p.stdout + p.stderr[-2000:]
        return _verdict(p.stdout)

    # fresh run: per-step saves, then cleared on success
    first = attempt()
    assert first["ok"] and first["burnin_step"] == 5
    assert first["burnin_checkpoint_saved"] == 5
    assert first["burnin_checkpoint_cleared"] == 1
    assert "burnin_resumed_step" not in first
    assert not ckpt.exists()

    # preempted run left a checkpoint behind → resume continues the count;
    # an orphaned mid-save tmp file (preemption between savez and replace)
    # must be swept, not accumulate on the PVC
    rng = np.random.default_rng(0)
    np.savez(ckpt, w=rng.normal(size=(256, 256)).astype(np.float32), step=3)
    orphan = tmp_path / "burnin_p0.npz.tmp.npz"
    orphan.write_bytes(b"half-written")
    second = attempt()
    assert second["ok"]
    assert second["burnin_resumed_step"] == 3
    assert second["burnin_step"] == 8
    assert not ckpt.exists()
    assert not orphan.exists()

    # corrupt checkpoint: JSON verdict with the error, exit 1, no traceback
    ckpt.write_bytes(b"not a zipfile")
    bad = attempt(expect_rc=1)
    assert bad["ok"] is False
    assert bad["burnin_checkpoint_ok"] is False
    assert "restore" in bad["checkpoint_error"]
    ckpt.unlink()

    # stale checkpoint from a different script revision (wrong shape):
    # loads cleanly, so shape validation must catch it inside the contract
    np.savez(ckpt, w=rng.normal(size=(128, 128)).astype(np.float32), step=2)
    stale = attempt(expect_rc=1)
    assert stale["ok"] is False
    assert stale["burnin_checkpoint_ok"] is False
    assert "stale checkpoint" in stale["checkpoint_error"]
    ckpt.unlink()

    # remote URI: the bundle must refuse loudly (it would otherwise write
    # to a literal local ./gs:/… directory on ephemeral disk)
    env["TPU_SMOKETEST_CHECKPOINT_DIR"] = "gs://bkt/ckpt"
    remote = attempt(expect_rc=1)
    assert remote["ok"] is False
    assert remote["burnin_checkpoint_ok"] is False
    assert "remote URI" in remote["checkpoint_error"]


# a 2-process "preempted attempt": jax.distributed world that collectively
# saves a step-3 checkpoint and exits WITHOUT clearing — exactly the state a
# preemption leaves behind for the next Job attempt to resume from
_SEED_SCRIPT = """
import os, sys
sys.path.insert(0, r'%s')
from nvidia_terraform_modules_tpu.parallel import (
    build_mesh, make_rules, maybe_initialize_distributed, plan_mesh)
maybe_initialize_distributed(os.environ)
import jax
from nvidia_terraform_modules_tpu.models import (
    BurnInConfig, Checkpointer, init_params)
rules = make_rules(build_mesh(plan_mesh(len(jax.devices()))))
cfg = BurnInConfig(batch=8)
with Checkpointer(os.environ["TPU_SMOKETEST_CHECKPOINT_DIR"]) as c:
    c.save(3, init_params(jax.random.PRNGKey(0), cfg, rules))
print('{"seeded": 3}')
""" % ROOT


@pytest.mark.slow
def test_package_runner_burnin_checkpoint_two_hosts(tmp_path):
    """The orbax path in a real 2-process jax.distributed world: a fresh
    pair saves collectively (each host writes only its shards) and clears
    on success; a second pair resumes from a collectively-seeded step-3
    checkpoint and continues the count."""
    runner = _pkg_runner(tmp_path)
    ckpt = tmp_path / "ckpt"
    env = {"TPU_SMOKETEST_LEVEL": "burnin",
           "TPU_SMOKETEST_CHECKPOINT_DIR": str(ckpt)}

    # fresh pair: per-step collective saves, cleared on success
    results = _run_pair(runner, env, port=8495)
    for rc, out, err in results:
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        verdict = _verdict(out)
        assert verdict["ok"] is True
        assert verdict["burnin_step"] == 5
        assert verdict["burnin_checkpoint_saved"] == 5
        assert "burnin_resumed_step" not in verdict
    # clear() snapshots the step list on every process BEFORE any delete
    # (lockstep barrier), so both report the full retained count: 2 steps
    # (max_to_keep=2 after 5 per-step saves), and the directory is empty
    cleared = {_verdict(out)["process_id"]: _verdict(out).get(
        "burnin_checkpoint_cleared") for _, out, _ in results}
    assert cleared == {0: 2, 1: 2}
    assert not ckpt.exists() or not any(
        p.is_dir() and p.name.isdigit() for p in ckpt.iterdir())

    # preempted pair left a step-3 checkpoint → the next pair resumes it
    seed = tmp_path / "seed_ckpt.py"
    seed.write_text(_SEED_SCRIPT)
    for rc, out, err in _run_pair(str(seed), env, port=8496):
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
    results = _run_pair(runner, env, port=8497)
    for rc, out, err in results:
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        verdict = _verdict(out)
        assert verdict["ok"] is True
        assert verdict["burnin_resumed_step"] == 3
        assert verdict["burnin_step"] == 8


@pytest.mark.slow
def test_standalone_script_full_level_two_hosts():
    """Level full across 2 processes: the MoE all-to-all dispatch leg and
    the 2-stage pipeline step must run over the REAL process boundary —
    the fabric proof the apply-gating Job sells (round-2 VERDICT item 3).
    The pipeline's pp=2 split spans the two hosts (devices 0-3 vs 4-7)."""
    script = os.path.join(ROOT, "gke-tpu", "scripts", "tpu_smoketest.py")
    results = _run_pair(script, {"TPU_SMOKETEST_LEVEL": "full"}, port=8498)
    for rc, out, err in results:
        assert rc == 0, f"stdout={out!r}\nstderr={err[-2000:]!r}"
        verdict = _verdict(out)
        assert verdict["ok"] is True
        assert verdict["alltoall_ok"] is True
        assert verdict["alltoall_gibps"] > 0
        assert verdict["moe_ok"] is True
        assert verdict["pipeline_ok"] is True
        assert verdict["burnin_ok"] is True
