# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Fused int8-weight matmul kernel + QTensor dispatch (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nvidia_terraform_modules_tpu.models.quantize import (
    QTensor,
    quantize,
    quantize_params,
)
from nvidia_terraform_modules_tpu.ops.int8_matmul import (
    int8_matmul,
    int8_matmul_ref,
)


def _rand_q(key, shape):
    return jax.random.randint(key, shape, -127, 128, jnp.int32).astype(
        jnp.int8)


@pytest.mark.parametrize("m", [8, 5])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_kernel_matches_reference(m, dtype):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(k1, (m, 256), dtype)
    q = _rand_q(k2, (256, 384))
    scale = jax.random.uniform(k3, (384,), jnp.float32, 0.01, 0.1)
    got = int8_matmul(x, q, scale, interpret=True,
                      block_m=128, block_n=128, block_k=128)
    want = int8_matmul_ref(x, q, scale)
    assert got.shape == (m, 384) and got.dtype == dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=1e-2)


def test_kernel_transpose_rhs_matches_reference():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    x = jax.random.normal(k1, (8, 256), jnp.float32)
    q = _rand_q(k2, (384, 256))                      # [N, K] storage
    scale = jax.random.uniform(k3, (384,), jnp.float32, 0.01, 0.1)
    got = int8_matmul(x, q, scale, transpose_rhs=True, interpret=True,
                      block_m=128, block_n=128, block_k=128)
    want = int8_matmul_ref(x, q, scale, transpose_rhs=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)


def test_kernel_multiblock_k_accumulates():
    """K spanning several k-blocks exercises the scratch accumulator."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    x = jax.random.normal(k1, (16, 512), jnp.float32)
    q = _rand_q(k2, (512, 128))
    scale = jnp.full((128,), 0.02, jnp.float32)
    got = int8_matmul(x, q, scale, interpret=True,
                      block_m=128, block_n=128, block_k=128)
    want = int8_matmul_ref(x, q, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)


def test_qtensor_matmul_matches_dequant():
    w = jax.random.normal(jax.random.PRNGKey(3), (64, 96), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 7, 64), jnp.float32)
    q, s = quantize(w)
    qt = QTensor(q, s.reshape(-1), scale_axis=1, dtype=jnp.float32)
    got = x @ qt
    want = x @ (q.astype(jnp.float32) * s)
    assert got.shape == (2, 7, 96)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)


def test_qtensor_tied_head_and_gather():
    """The embedding's two roles: row gather and transposed tied head."""
    emb = jax.random.normal(jax.random.PRNGKey(5), (50, 32), jnp.float32)
    q, s = quantize(emb, axis=0)                     # per-row scales
    qt = QTensor(q, s.reshape(-1), scale_axis=0, dtype=jnp.float32)
    deq = q.astype(jnp.float32) * s                  # [50, 32]

    idx = jnp.array([[3, 11], [0, 49]])
    np.testing.assert_allclose(np.asarray(qt[idx]), np.asarray(deq[idx]),
                               rtol=1e-6, atol=1e-6)
    assert qt.T.shape == (32, 50)
    x = jax.random.normal(jax.random.PRNGKey(6), (4, 32), jnp.float32)
    np.testing.assert_allclose(np.asarray(x @ qt.T), np.asarray(x @ deq.T),
                               rtol=1e-3, atol=1e-3)


def test_qtensor_rejects_scale_on_contraction_axis():
    q = _rand_q(jax.random.PRNGKey(7), (16, 24))
    x = jnp.ones((2, 16))
    qt = QTensor(q, jnp.ones((16,)), scale_axis=0, dtype=jnp.float32)
    with pytest.raises(TypeError, match="contraction axis"):
        _ = x @ qt                                   # per-row scales, untransposed
    with pytest.raises(TypeError, match="transposed"):
        _ = qt.T[jnp.array([0])]


def test_qtensor_roundtrips_through_jit_and_tree():
    """Pytree registration: QTensor params cross a jit boundary intact."""
    w = jax.random.normal(jax.random.PRNGKey(8), (32, 48), jnp.float32)
    q, s = quantize(w)
    qt = QTensor(q, s.reshape(-1), scale_axis=1, dtype=jnp.float32)

    @jax.jit
    def f(x, qt):
        return x @ qt

    x = jnp.ones((3, 32))
    np.testing.assert_allclose(np.asarray(f(x, qt)), np.asarray(x @ qt),
                               rtol=1e-6, atol=1e-6)


def test_quantize_params_layout():
    from nvidia_terraform_modules_tpu.models import BurnInConfig, init_params

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=8, batch=2, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    qp = quantize_params(params, dtype=jnp.float32)
    assert isinstance(qp["embed"], QTensor) and qp["embed"].scale_axis == 0
    assert qp["embed"].scale.shape == (cfg.vocab,)
    layer = qp["layers"][0]
    assert isinstance(layer["wq"], QTensor) and layer["wq"].scale_axis == 1
    # norm scales pass through bit-exact, unquantized
    assert jnp.array_equal(qp["out_norm"], params["out_norm"])
    assert jnp.array_equal(layer["attn_norm"],
                           params["layers"][0]["attn_norm"])
