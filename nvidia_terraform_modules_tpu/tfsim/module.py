# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Module model: a directory of ``.tf`` files → structured Module object."""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

from . import ast as A
from .parser import parse_hcl


@dataclasses.dataclass
class Variable:
    name: str
    type: Optional[str]
    default: Optional[A.Expr]
    description: Optional[str]
    sensitive: bool
    nullable: bool
    validations: list[A.Block]
    file: str
    line: int
    type_expr: Optional[A.Expr] = None  # raw type AST (for optional() defaults)


@dataclasses.dataclass
class Output:
    name: str
    expr: Optional[A.Expr]
    description: Optional[str]
    sensitive: bool
    file: str
    line: int


@dataclasses.dataclass
class Resource:
    mode: str                 # "managed" | "data"
    type: str
    name: str
    body: A.Body
    file: str
    line: int

    @property
    def address(self) -> str:
        prefix = "data." if self.mode == "data" else ""
        return f"{prefix}{self.type}.{self.name}"


@dataclasses.dataclass
class ModuleCall:
    name: str
    body: A.Body
    file: str
    line: int


@dataclasses.dataclass
class Provider:
    name: str
    alias: Optional[str]
    body: A.Body
    file: str


@dataclasses.dataclass
class Backend:
    """A ``terraform { backend "TYPE" { … } }`` declaration.

    Terraform forbids variables/references in backend config (it is read
    before any evaluation context exists), so ``config`` holds only the
    literal attributes; the loader rejects anything else with terraform's
    own "Variables may not be used here" stance.
    """

    type: str
    config: dict[str, Any]
    file: str
    line: int


@dataclasses.dataclass
class Module:
    path: str
    variables: dict[str, Variable]
    locals: dict[str, A.Expr]
    resources: dict[str, Resource]          # address → Resource
    data_sources: dict[str, Resource]       # address → Resource
    outputs: dict[str, Output]
    module_calls: dict[str, ModuleCall]
    providers: list[Provider]
    required_providers: dict[str, dict[str, Any]]
    required_version: Optional[str]
    files: dict[str, A.Body]
    moved: list[A.Block] = dataclasses.field(default_factory=list)
    checks: list[A.Block] = dataclasses.field(default_factory=list)
    backend: Optional[Backend] = None
    imports: list[A.Block] = dataclasses.field(default_factory=list)

    def resource(self, type_: str, name: str) -> Resource:
        return self.resources[f"{type_}.{name}"]


def _str_attr(body: A.Body, name: str) -> Optional[str]:
    a = body.attr(name)
    if a is None:
        return None
    if isinstance(a.expr, A.Literal) and isinstance(a.expr.value, str):
        return a.expr.value
    if isinstance(a.expr, A.Traversal):
        return a.expr.path_str()
    return None


def _bool_attr(body: A.Body, name: str, default: bool = False) -> bool:
    a = body.attr(name)
    if a is None:
        return default
    if isinstance(a.expr, A.Literal) and isinstance(a.expr.value, bool):
        return a.expr.value
    return default


def _type_expr_str(body: A.Body) -> Optional[str]:
    a = body.attr("type")
    if a is None:
        return None
    return _render_type(a.expr)


def _render_type(e: A.Expr) -> str:
    """Render a type expression back to valid HCL (for docs / messages)."""
    if isinstance(e, A.Traversal):
        base = e.root
        return base
    if isinstance(e, A.Call):
        inner = ", ".join(_render_type(x) for x in e.args)
        return f"{e.name}({inner})"
    if isinstance(e, A.ObjectExpr):
        inner = ", ".join(
            f"{it.key.value if isinstance(it.key, A.Literal) else '?'} = "
            f"{_render_type(it.value)}"
            for it in e.items
        )
        return f"{{{inner}}}"
    if isinstance(e, A.TupleExpr):
        return f"[{', '.join(_render_type(x) for x in e.items)}]"
    if isinstance(e, A.Literal):
        # HCL literals, not Python reprs: true/false, quoted strings
        if isinstance(e.value, bool):
            return "true" if e.value else "false"
        if isinstance(e.value, str):
            return f'"{e.value}"'
        if e.value is None:
            return "null"
        return str(e.value)
    return type(e).__name__


class ModuleLoadError(ValueError):
    pass


def load_module(path: str) -> Module:
    """Parse all ``*.tf`` files directly inside ``path`` into a Module."""
    tf_files = sorted(
        f for f in os.listdir(path) if f.endswith(".tf") and
        os.path.isfile(os.path.join(path, f))
    )
    if not tf_files:
        raise ModuleLoadError(f"no .tf files in {path}")

    mod = Module(
        path=path, variables={}, locals={}, resources={}, data_sources={},
        outputs={}, module_calls={}, providers=[], required_providers={},
        required_version=None, files={},
    )

    for fname in tf_files:
        full = os.path.join(path, fname)
        with open(full, "r") as fh:
            body = parse_hcl(fh.read(), filename=full)
        mod.files[fname] = body
        for attr in body.attributes:
            raise ModuleLoadError(
                f"{full}:{attr.line}: top-level attribute {attr.name!r} not allowed"
            )
        for blk in body.blocks:
            _ingest(mod, blk, fname)
    return mod


def _ingest(mod: Module, blk: A.Block, fname: str) -> None:
    full = os.path.join(mod.path, fname)

    def dup(kind: str, key: str):
        raise ModuleLoadError(f"{full}:{blk.line}: duplicate {kind} {key!r}")

    if blk.type == "variable":
        if len(blk.labels) != 1:
            raise ModuleLoadError(f"{full}:{blk.line}: variable needs exactly one label")
        name = blk.labels[0]
        if name in mod.variables:
            dup("variable", name)
        d = blk.body.attr("default")
        t = blk.body.attr("type")
        mod.variables[name] = Variable(
            name=name,
            type=_type_expr_str(blk.body),
            default=d.expr if d else None,
            description=_str_attr(blk.body, "description"),
            sensitive=_bool_attr(blk.body, "sensitive"),
            nullable=_bool_attr(blk.body, "nullable", default=True),
            validations=blk.body.blocks_of("validation"),
            file=fname, line=blk.line,
            type_expr=t.expr if t else None,
        )
    elif blk.type == "locals":
        for attr in blk.body.attributes:
            if attr.name in mod.locals:
                dup("local", attr.name)
            mod.locals[attr.name] = attr.expr
    elif blk.type == "resource":
        if len(blk.labels) != 2:
            raise ModuleLoadError(f"{full}:{blk.line}: resource needs two labels")
        r = Resource("managed", blk.labels[0], blk.labels[1], blk.body, fname, blk.line)
        if r.address in mod.resources:
            dup("resource", r.address)
        mod.resources[r.address] = r
    elif blk.type == "data":
        if len(blk.labels) != 2:
            raise ModuleLoadError(f"{full}:{blk.line}: data needs two labels")
        r = Resource("data", blk.labels[0], blk.labels[1], blk.body, fname, blk.line)
        if r.address in mod.data_sources:
            dup("data source", r.address)
        mod.data_sources[r.address] = r
    elif blk.type == "output":
        if len(blk.labels) != 1:
            raise ModuleLoadError(f"{full}:{blk.line}: output needs exactly one label")
        name = blk.labels[0]
        if name in mod.outputs:
            dup("output", name)
        v = blk.body.attr("value")
        mod.outputs[name] = Output(
            name=name, expr=v.expr if v else None,
            description=_str_attr(blk.body, "description"),
            sensitive=_bool_attr(blk.body, "sensitive"),
            file=fname, line=blk.line,
        )
    elif blk.type == "module":
        if len(blk.labels) != 1:
            raise ModuleLoadError(f"{full}:{blk.line}: module call needs one label")
        name = blk.labels[0]
        if name in mod.module_calls:
            dup("module call", name)
        mod.module_calls[name] = ModuleCall(name, blk.body, fname, blk.line)
    elif blk.type == "provider":
        mod.providers.append(
            Provider(blk.labels[0] if blk.labels else "?",
                     _str_attr(blk.body, "alias"), blk.body, fname)
        )
    elif blk.type == "terraform":
        rv = blk.body.attr("required_version")
        if rv and isinstance(rv.expr, A.Literal):
            mod.required_version = rv.expr.value
        for rp in blk.body.blocks_of("required_providers"):
            for attr in rp.body.attributes:
                spec: dict[str, Any] = {}
                if isinstance(attr.expr, A.ObjectExpr):
                    for item in attr.expr.items:
                        if isinstance(item.key, A.Literal) and isinstance(item.value, A.Literal):
                            spec[str(item.key.value)] = item.value.value
                elif isinstance(attr.expr, A.Literal) and \
                        isinstance(attr.expr.value, str):
                    # legacy shorthand: google = "~> 5.0" is a bare
                    # version constraint (terraform 0.12 form)
                    spec["version"] = attr.expr.value
                mod.required_providers[attr.name] = spec
        for bk in blk.body.blocks_of("backend"):
            if mod.backend is not None:
                raise ModuleLoadError(
                    f"{full}:{bk.line}: duplicate backend block — a "
                    f"configuration can only declare one backend")
            if not bk.labels:
                raise ModuleLoadError(
                    f"{full}:{bk.line}: backend block needs a type label "
                    f'(e.g. backend "gcs")')
            config: dict[str, Any] = {}
            for attr in bk.body.attributes:
                if not isinstance(attr.expr, A.Literal):
                    # terraform reads backend config before any eval
                    # context exists: "Variables may not be used here."
                    raise ModuleLoadError(
                        f"{full}:{attr.line}: backend {attr.name!r} must "
                        f"be a literal — variables may not be used in "
                        f"backend configuration (terraform semantics)")
                config[attr.name] = attr.expr.value
            mod.backend = Backend(type=bk.labels[0], config=config,
                                  file=fname, line=bk.line)
    elif blk.type == "moved":
        mod.moved.append(blk)
    elif blk.type == "import":
        # config-driven import (terraform 1.5+): `import { to = a.b
        # id = "…" }` — adoption becomes part of the reviewed plan
        # instead of an out-of-band CLI step
        mod.imports.append(blk)
    elif blk.type == "check":
        mod.checks.append(blk)
    else:
        raise ModuleLoadError(
            f"{full}:{blk.line}: unsupported top-level block {blk.type!r}"
        )
