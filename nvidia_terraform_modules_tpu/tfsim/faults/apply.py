# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The stepwise apply engine: one operation at a time, fault-aware.

``apply_plan`` (:mod:`..state`) realises a diff atomically — correct,
but it cannot fail halfway. This engine walks the same diff as the
sequence of operations a real ``terraform apply`` performs (deletes in
reverse dependency order, then creates/updates/replaces in dependency
order), runs each through the :class:`..faults.control_plane.ControlPlane`,
and on terminal failure does what terraform does:

- every already-completed operation is **persisted** to the returned
  state (no orphans: a created resource is never forgotten);
- a half-created resource (preemption or timeout mid-create) is
  recorded **tainted**, so the next apply replaces it instead of
  creating a duplicate;
- the remaining operations are simply not performed — a second apply
  plans exactly the leftover work and converges.

When every operation succeeds the engine returns ``apply_plan``'s own
result, so a profile that injects nothing is bit-identical to the
atomic path.
"""

from __future__ import annotations

import dataclasses

from ..plan import Plan, instance_apply_order
from ..state import Diff, State, apply_plan, diff, rendered_instances
from .control_plane import (
    DEFAULT_TIMEOUT_S,
    ControlPlane,
    CrashSignal,
    FaultError,
    TerminalFault,
    parse_duration,
)
from .profile import PARTIAL_CREATE


class SimulatedCrash(FaultError):
    """The profile killed the apply process. Carries the partial
    :class:`ApplyOutcome` so the CLI can persist completed work before
    "dying" — and, unlike every other failure, the state **lock is left
    behind** (a crashed process releases nothing), so the recovery
    playbook's ``force-unlock`` step is exercised too."""

    def __init__(self, outcome: "ApplyOutcome"):
        super().__init__(
            "simulated crash: apply died mid-run (the state lock, if "
            "held, was left behind — break it with `tfsim force-unlock`)")
        self.outcome = outcome


@dataclasses.dataclass
class OpFailure:
    """The terminal failure that interrupted an apply."""

    address: str
    op: str            # create | update | delete
    kind: str          # fault kind ("timeout" for an exhausted budget)
    message: str
    attempts: int


@dataclasses.dataclass
class ApplyOutcome:
    state: State
    failure: OpFailure | None = None
    crashed: bool = False
    completed: list = dataclasses.field(default_factory=list)  # (addr, op)
    mutated: bool = False    # state differs from prior → worth persisting

    @property
    def ok(self) -> bool:
        return self.failure is None and not self.crashed


def _timeouts_of(attrs) -> dict:
    """The resource's rendered ``timeouts {}`` block, if any. Blocks
    evaluate to a list of one object; tolerate both shapes."""
    t = (attrs or {}).get("timeouts")
    if isinstance(t, list) and t and isinstance(t[0], dict):
        return t[0]
    return t if isinstance(t, dict) else {}


def operation_timeout_s(op: str, planned_attrs, prior_attrs=None) -> float:
    """The ``timeouts {}`` budget for one operation, in simulated
    seconds. Deletes of resources gone from config take the budget the
    *applied* attributes carry (the config block that created them);
    anything undeclared gets the provider default."""
    spec = _timeouts_of(planned_attrs) or _timeouts_of(prior_attrs)
    raw = spec.get(op)
    if isinstance(raw, str) and raw.strip():
        budget = parse_duration(raw, what=f"timeouts.{op}")
        if budget <= 0:
            raise ValueError(
                f"invalid timeouts.{op} duration {raw!r}: an operation "
                f"budget must be positive")
        return budget
    return DEFAULT_TIMEOUT_S


def _operations(plan: Plan, d: Diff) -> list[tuple[str, str]]:
    """The diff as an ordered operation list: deletes first in reverse
    dependency order (terraform tears down leaves before roots), then
    creates/updates in dependency order, a replace expanding to its
    delete + create pair (destroy-before-create default)."""
    ops: list[tuple[str, str]] = []
    for addr in reversed(instance_apply_order(plan, d.by_action("delete"))):
        ops.append((addr, "delete"))
    changes = (d.by_action("create") + d.by_action("update") +
               d.by_action("replace"))
    for addr in instance_apply_order(plan, changes):
        act = d.actions[addr]
        if act == "replace":
            ops.append((addr, "delete"))
            ops.append((addr, "create"))
        else:
            ops.append((addr, act))
    return ops


def _partial_state(prior: State | None, planned: dict,
                   completed: list[tuple[str, str]],
                   taint: str | None = None) -> tuple[State, bool]:
    """The state an interrupted apply persists: prior advanced by every
    completed operation, plus the optionally-tainted half-created
    resource. Returns ``(state, mutated)``."""
    resources = dict(prior.resources) if prior else {}
    tainted = set(prior.tainted) if prior else set()
    for addr, op in completed:
        if op == "delete":
            resources.pop(addr, None)
            tainted.discard(addr)
        else:
            resources[addr] = planned[addr]
            tainted.discard(addr)   # a completed replace consumed the taint
    if taint is not None:
        resources[taint] = planned[taint]
        tainted.add(taint)
    mutated = (resources != (dict(prior.resources) if prior else {}) or
               tainted != (set(prior.tainted) if prior else set()))
    serial = (prior.serial if prior else 0) + (1 if mutated else 0)
    # outputs are NOT refreshed: the plan did not complete, and claiming
    # its outputs would hand the operator values the infrastructure
    # doesn't have (the converging re-apply refreshes them)
    return State(resources=resources, serial=serial,
                 outputs=dict(prior.outputs) if prior else {},
                 tainted=tainted,
                 lineage=prior.lineage if prior else ""), mutated


def run_apply(plan: Plan, prior: State | None, cp: ControlPlane,
              targets: list[str] | None = None,
              d: Diff | None = None, log=None) -> ApplyOutcome:
    """Apply ``plan`` over ``prior`` one operation at a time.

    Returns an :class:`ApplyOutcome`; raises :class:`SimulatedCrash`
    (carrying the partial outcome) when the profile kills the process.
    On full success the returned state comes from :func:`..state.apply_plan`
    — the fault layer adds no drift to the happy path.
    """
    if d is None:
        d = diff(plan, prior, targets)
    planned = rendered_instances(plan)
    prior_res = prior.resources if prior else {}
    ops = _operations(plan, d)
    # validate EVERY timeouts{} budget before the first operation runs:
    # a malformed duration must fail the apply up front (state untouched),
    # never halfway through — that would orphan the completed work
    timeouts: dict[tuple[str, str], float] = {}
    for addr, op in ops:
        try:
            timeouts[(addr, op)] = operation_timeout_s(
                op, planned.get(addr), prior_res.get(addr))
        except ValueError as ex:
            raise ValueError(f"{addr}: {ex}") from None
    completed: list[tuple[str, str]] = []
    for addr, op in ops:
        try:
            cp.run_operation(addr, op, timeouts[addr, op], log=log)
        except CrashSignal:
            state, mutated = _partial_state(prior, planned, completed)
            raise SimulatedCrash(ApplyOutcome(
                state=state, crashed=True, completed=completed,
                mutated=mutated)) from None
        except TerminalFault as ex:
            taint = addr if (op == "create" and
                             ex.kind in PARTIAL_CREATE) else None
            state, mutated = _partial_state(prior, planned, completed,
                                            taint=taint)
            return ApplyOutcome(
                state=state,
                failure=OpFailure(address=addr, op=op, kind=ex.kind,
                                  message=str(ex), attempts=ex.attempts),
                completed=completed, mutated=mutated)
        completed.append((addr, op))
    return ApplyOutcome(state=apply_plan(plan, prior, targets, d=d),
                        completed=completed, mutated=not d.is_noop)
