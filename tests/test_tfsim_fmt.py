# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Tests for tfsim.fmt — the ``terraform fmt`` stand-in.

The reference's pre-checkin gate is ``terraform fmt`` run manually
(``/root/reference/CONTRIBUTING.md:12``); here the gate is automated: every
``.tf`` file in the repo must already be canonical, and the formatter itself
is unit-tested on the behaviours terraform fmt is known for.
"""

import glob
import os

import pytest

from nvidia_terraform_modules_tpu.tfsim.fmt import check_text, format_text

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALL_TF = sorted(
    glob.glob(os.path.join(ROOT, "gke", "**", "*.tf"), recursive=True)
    + glob.glob(os.path.join(ROOT, "gke-tpu", "**", "*.tf"), recursive=True)
)


def test_repo_has_tf_files():
    assert len(ALL_TF) > 20


@pytest.mark.parametrize("path", ALL_TF, ids=lambda p: os.path.relpath(p, ROOT))
def test_repo_tf_is_canonical(path):
    with open(path, encoding="utf-8") as f:
        text = f.read()
    diffs = check_text(text, path)
    assert diffs == [], "\n".join(str(d) for d in diffs)


def test_idempotent_on_repo():
    for path in ALL_TF[:10]:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        once = format_text(text)
        assert format_text(once) == once


def test_equals_alignment():
    src = (
        'resource "a_b" "c" {\n'
        "  name = 1\n"
        "  much_longer_name = 2\n"
        "}\n"
    )
    want = (
        'resource "a_b" "c" {\n'
        "  name             = 1\n"
        "  much_longer_name = 2\n"
        "}\n"
    )
    assert format_text(src) == want


def test_alignment_groups_break_on_blank_lines():
    src = (
        "locals {\n"
        "  a = 1\n"
        "\n"
        "  longer = 2\n"
        "}\n"
    )
    # blank line splits the run: no cross-group alignment
    assert format_text(src) == src


def test_reindent_from_brackets():
    src = (
        'variable "v" {\n'
        "      type = object({\n"
        "  a = optional(number, 1)\n"
        "        })\n"
        "}\n"
    )
    want = (
        'variable "v" {\n'
        "  type = object({\n"
        "    a = optional(number, 1)\n"
        "  })\n"
        "}\n"
    )
    assert format_text(src) == want


def test_partial_close_line_sits_at_opener_level():
    src = (
        'variable "v" {\n'
        "  type = object({\n"
        "    rl = optional(list(object({\n"
        "      rt = string\n"
        "    })), [])\n"
        "  })\n"
        "}\n"
    )
    assert format_text(src) == src


def test_heredoc_blank_lines_preserved():
    src = (
        "locals {\n"
        "  s = <<-EOT\n"
        "    line1\n"
        "\n"
        "\n"
        "    line2\n"
        "  EOT\n"
        "}\n"
    )
    assert format_text(src) == src


def test_block_comment_blank_lines_preserved():
    src = "/* a\n\n\n   b */\nlocals {\n  a = 1\n}\n"
    assert format_text(src) == src


def test_heredoc_body_is_verbatim():
    src = (
        'variable "v" {\n'
        "  description = <<-EOT\n"
        "       raggedy   text = kept,   as-is\n"
        "    second line\n"
        "  EOT\n"
        "  type = string\n"
        "}\n"
    )
    assert format_text(src) == src


def test_trailing_whitespace_and_blank_runs():
    src = "locals {  \n  a = 1\t\n\n\n\n  b = 2\n}\n\n\n"
    want = "locals {\n  a = 1\n\n  b = 2\n}\n"
    assert format_text(src) == want


def test_interpolation_braces_are_not_structure():
    src = (
        "locals {\n"
        '  m = "${var.p}.svc[${local.ns}/x]"\n'
        "  n = 1\n"
        "}\n"
    )
    assert format_text(src) == src


def test_comparison_ops_not_treated_as_attrs():
    src = (
        "locals {\n"
        "  ok = var.a == 1\n"
        "  very_long_name = var.b != 2\n"
        "}\n"
    )
    want = (
        "locals {\n"
        "  ok             = var.a == 1\n"
        "  very_long_name = var.b != 2\n"
        "}\n"
    )
    assert format_text(src) == want


def test_check_reports_line_numbers():
    diffs = check_text("locals {\n      a = 1\n}\n", "x.tf")
    assert diffs and diffs[0].path == "x.tf" and diffs[0].line == 2


def test_fmt_covers_tftest_files(tmp_path, capsys):
    """fmt -check on a module dir reaches its tests/*.tftest.hcl files
    (terraform fmt formats test files too)."""
    from nvidia_terraform_modules_tpu.tfsim.__main__ import main

    (tmp_path / "main.tf").write_text('locals {\n  a = 1\n}\n')
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "t.tftest.hcl").write_text(
        'run "x" {\n    command   =    plan\n}\n')   # mis-aligned
    assert main(["fmt", "-check", str(tmp_path)]) == 1
    assert "t.tftest.hcl" in capsys.readouterr().out
    # rewrite mode fixes it in place
    assert main(["fmt", str(tmp_path)]) == 0
    capsys.readouterr()
    assert main(["fmt", "-check", str(tmp_path)]) == 0
    assert (tests / "t.tftest.hcl").read_text() == \
        'run "x" {\n  command = plan\n}\n'
