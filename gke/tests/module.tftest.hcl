# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Native-format test suite for the gke (GPU-parity) module, run by
# `tfsim test`. Mirrors the reference module's capability surface: cluster +
# CPU/GPU pools + GPU Operator helm release (/root/reference/gke/main.tf),
# exercised as offline golden plans.

variables {
  project_id   = "test-project"
  cluster_name = "gpu-test"
}

run "defaults" {
  command = plan

  assert {
    condition     = google_container_cluster.this.remove_default_node_pool == true
    error_message = "the default node pool must be removed (reference gke/main.tf:45)"
  }
  assert {
    condition     = google_container_node_pool.gpu[0].node_config[0].guest_accelerator[0].count == 1
    error_message = "default GPU pool carries one accelerator per node"
  }
  assert {
    condition     = helm_release.gpu_operator[0].atomic == true
    error_message = "operator install must be atomic (self-healing apply)"
  }
  assert {
    condition     = output.cluster_name == var.cluster_name
    error_message = "cluster name must round-trip to the output"
  }
}

# BASELINE config 1: CPU-only cluster — no GPU pool, no operator install.
run "cpu_only" {
  command = plan

  variables {
    gpu_pool     = { enabled = false }
    gpu_operator = { enabled = false }
  }

  assert {
    condition     = length(google_container_node_pool.gpu) == 0
    error_message = "gpu_pool.enabled = false must plan no GPU pool"
  }
  assert {
    condition     = length(helm_release.gpu_operator) == 0
    error_message = "operator disabled must plan no helm release"
  }
  assert {
    condition     = length(kubernetes_namespace_v1.gpu_operator) == 0
    error_message = "operator disabled must plan no namespace"
  }
}

# Control-plane security: CMEK secrets encryption (reference EKS
# eks/main.tf:64-72 parity) and Google Groups RBAC (reference AKS
# aks/main.tf:36-40 parity).
run "secrets_encryption_creates_key_and_grant" {
  command = plan

  variables {
    database_encryption          = { enabled = true }
    authenticator_security_group = "gke-security-groups@example.com"
  }

  assert {
    condition     = google_container_cluster.this.database_encryption[0].state == "ENCRYPTED"
    error_message = "enabled CMEK must render an ENCRYPTED database_encryption block"
  }
  assert {
    condition     = length(google_kms_key_ring.secrets) == 1 && length(google_kms_crypto_key.secrets) == 1
    error_message = "no BYO key: the module must create keyring + crypto key"
  }
  assert {
    condition     = google_kms_crypto_key.secrets[0].rotation_period == "7776000s"
    error_message = "created key must rotate (reference enable_key_rotation parity)"
  }
  assert {
    condition     = length(google_kms_crypto_key_iam_member.gke_agent) == 1
    error_message = "the GKE service agent needs EncrypterDecrypter on the key"
  }
  assert {
    condition     = google_container_cluster.this.authenticator_groups_config[0].security_group == "gke-security-groups@example.com"
    error_message = "the RBAC umbrella group must reach the control plane"
  }
}

run "secrets_encryption_byo_key" {
  command = plan

  variables {
    database_encryption = {
      enabled      = true
      kms_key_name = "projects/p/locations/r/keyRings/kr/cryptoKeys/k"
    }
  }

  assert {
    condition     = length(google_kms_key_ring.secrets) == 0 && length(google_kms_crypto_key.secrets) == 0
    error_message = "BYO key must not create module-owned KMS resources"
  }
  assert {
    condition     = google_container_cluster.this.database_encryption[0].key_name == "projects/p/locations/r/keyRings/kr/cryptoKeys/k"
    error_message = "the BYO key must reach the cluster block verbatim"
  }
}

# An unrendered dynamic block reads as provider-computed in the simulator,
# so "defaults off" is asserted through the countable module-owned
# resources the feature would have created.
run "security_defaults_off" {
  command = plan

  assert {
    condition     = length(google_kms_key_ring.secrets) == 0 && length(google_kms_crypto_key.secrets) == 0
    error_message = "no KMS resources unless encryption is enabled"
  }
  assert {
    condition     = length(google_kms_crypto_key_iam_member.gke_agent) == 0
    error_message = "no service-agent grant unless encryption is enabled"
  }
}
