# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""CLI entry: ``python -m nvidia_terraform_modules_tpu.smoketest``.

This is the command the ``gke-tpu`` smoke-test Job container runs. Env
contract (injected by the Job template in ``gke-tpu/smoketest.tf``):

- ``TPU_SMOKETEST_EXPECTED_DEVICES`` — chips this host must see after init;
- ``TPU_SMOKETEST_LEVEL`` — psum | probes | burnin | full;
- ``TPU_SMOKETEST_HOSTS`` / ``TPU_SMOKETEST_COORDINATOR`` /
  ``JOB_COMPLETION_INDEX`` — multi-host bootstrap (see parallel/multihost.py).
"""

import os
import sys

from .runner import run_smoketest


def _steer_platform() -> None:
    """Honour TPU_SMOKETEST_PLATFORM before the first backend init.

    Some rigs pre-import jax pinned to a TPU platform (sitecustomize) in a way
    that ignores ``JAX_PLATFORMS``; the config route still works as long as no
    device has been touched. In-cluster the default (TPU) is what we want; CPU
    smoke rigs set ``TPU_SMOKETEST_PLATFORM=cpu``.
    """
    plat = os.environ.get("TPU_SMOKETEST_PLATFORM")
    if not plat:
        return
    import jax

    try:
        jax.config.update("jax_platforms", plat)
    # graftlint: ignore[graft-silent-except] — best-effort steer only
    except Exception:   # the default platform selection stands
        pass


def main() -> int:
    _steer_platform()
    level = os.environ.get("TPU_SMOKETEST_LEVEL", "probes")
    result = run_smoketest(level=level)
    print(result.to_json(), flush=True)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
