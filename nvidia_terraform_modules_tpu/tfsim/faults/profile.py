# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Fault profiles: which faults land on which operations, deterministically.

A profile is a JSON file (``-fault-profile FILE``) listing fault specs::

    {"faults": [
      {"fault": "api-429", "resource": "google_container_node_pool.*",
       "op": "create", "prob": 0.5, "max": 2},
      {"fault": "tpu-stockout", "op": "create", "max": 1},
      {"fault": "state-write-failed", "prob": 0.2}
    ]}

Each spec matches operations by resource-address glob (``resource``,
default ``*``) and operation kind (``op``: ``create`` / ``update`` /
``delete`` / ``any``), fires with probability ``prob`` (default 1.0)
drawn from the seeded RNG (``-fault-seed N``), and injects at most
``max`` times per apply (default 1; retryable faults usually want a
small budget so the retry loop eventually wins).

Fault kinds mirror the failure classes the google provider actually
surfaces on TPU capacity:

==================== ========= ==============================================
kind                 class     semantics
==================== ========= ==============================================
``api-429``          retryable rate limit; capped exponential backoff
``api-500``          retryable transient server error; same backoff
``tpu-stockout``     terminal  no capacity for the slice; nothing created
``quota-exceeded``   terminal  project quota; nothing created
``preempted``        terminal  spot capacity created, then reclaimed —
                               the resource lands in state **tainted**
``state-write-failed`` special the state write itself fails; the CLI
                               emits ``errored.tfstate`` instead
``crash``            special   the process dies mid-apply: completed work
                               is persisted, the state **lock is left
                               behind** (break it with ``force-unlock``)
==================== ========= ==============================================

A retryable fault that never clears within the operation's ``timeouts``
budget becomes the terminal pseudo-kind ``timeout`` ("context deadline
exceeded"), which — like ``preempted`` — leaves the half-created
resource tainted: the provider may have partially provisioned it.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import random

RETRYABLE = {
    "api-429": "API rate limit exceeded (HTTP 429)",
    "api-500": "transient API server error (HTTP 500)",
}
TERMINAL = {
    "tpu-stockout": "TPU capacity stockout: no slice capacity available "
                    "in the location",
    "quota-exceeded": "quota exceeded for the project "
                      "(compute.googleapis.com)",
    "preempted": "spot/preemptible capacity was reclaimed during creation",
}
SPECIAL = {
    "state-write-failed": "the state write failed",
    "crash": "the apply process died mid-run",
}
KINDS = {**RETRYABLE, **TERMINAL, **SPECIAL}

# terminal create-failures after which the provider may have partially
# provisioned the resource: recorded in state AND tainted, so the next
# apply replaces instead of duplicating (terraform's own stance)
PARTIAL_CREATE = {"preempted", "timeout"}

OPS = ("create", "update", "delete")


@dataclasses.dataclass
class FaultSpec:
    """One fault rule: kind + where it lands + how often."""

    kind: str
    resource: str = "*"     # address glob (fnmatch)
    op: str = "any"         # create | update | delete | any
    prob: float = 1.0       # per-draw probability (seeded RNG)
    max: int = 1            # injection budget per apply
    injected: int = 0       # runtime counter (not part of the file format)

    def matches(self, address: str, op: str) -> bool:
        return (self.op in ("any", op) and
                fnmatch.fnmatchcase(address, self.resource))

    def draw(self, rng: random.Random) -> bool:
        """Consume one RNG draw; True when this spec fires (and has
        budget left). The draw happens even at prob 1.0 so the RNG
        stream — and therefore every downstream decision — depends only
        on the seed and the deterministic operation order."""
        if self.injected >= self.max:
            return False
        if rng.random() >= self.prob:
            return False
        self.injected += 1
        return True


@dataclasses.dataclass
class FaultProfile:
    specs: list[FaultSpec]

    def draw_operation_fault(self, address: str, op: str,
                             rng: random.Random) -> str | None:
        """The fault kind (if any) injected into one operation attempt.
        Specs are consulted in file order; the first that fires wins."""
        for spec in self.specs:
            if spec.kind == "state-write-failed":
                continue   # drawn at state-write time, not per operation
            if spec.matches(address, op) and spec.draw(rng):
                return spec.kind
        return None

    def draw_state_write_fault(self, rng: random.Random) -> bool:
        return any(spec.draw(rng) for spec in self.specs
                   if spec.kind == "state-write-failed")

    def reset(self) -> None:
        for spec in self.specs:
            spec.injected = 0


def _spec_from_raw(raw: dict, where: str) -> FaultSpec:
    if not isinstance(raw, dict):
        raise ValueError(f"{where}: each fault spec must be an object")
    kind = raw.get("fault")
    if kind not in KINDS:
        raise ValueError(
            f"{where}: unknown fault kind {kind!r} "
            f"(known: {', '.join(sorted(KINDS))})")
    op = raw.get("op", "any")
    if op not in OPS and op != "any":
        raise ValueError(
            f"{where}: op must be one of {', '.join(OPS)} or \"any\", "
            f"got {op!r}")
    resource = raw.get("resource", "*")
    if not isinstance(resource, str):
        raise ValueError(f"{where}: resource must be a glob string")
    prob = raw.get("prob", 1.0)
    if not isinstance(prob, (int, float)) or not 0.0 <= prob <= 1.0:
        raise ValueError(f"{where}: prob must be a number in [0, 1]")
    mx = raw.get("max", 1)
    if not isinstance(mx, int) or mx < 1:
        raise ValueError(f"{where}: max must be a positive integer")
    extra = set(raw) - {"fault", "resource", "op", "prob", "max"}
    if extra:
        raise ValueError(
            f"{where}: unknown key(s) {', '.join(sorted(extra))}")
    return FaultSpec(kind=kind, resource=resource,
                     op=op, prob=float(prob), max=mx)


def profile_from_dict(raw, where: str = "fault profile") -> FaultProfile:
    if not isinstance(raw, dict) or not isinstance(raw.get("faults"), list):
        raise ValueError(
            f'{where}: expected {{"faults": [ … ]}} at the top level')
    return FaultProfile(specs=[
        _spec_from_raw(s, f"{where}: faults[{i}]")
        for i, s in enumerate(raw["faults"])
    ])


def load_profile(path: str) -> FaultProfile:
    """Load and validate a fault-profile JSON file."""
    try:
        with open(path) as fh:
            raw = json.load(fh)
    except (OSError, json.JSONDecodeError) as ex:
        raise ValueError(f"cannot read fault profile {path!r}: {ex}") from ex
    return profile_from_dict(raw, where=path)


# The built-in chaos mix: every failure class the issue names, with
# probabilities tuned so an 8-seed sweep reliably exercises clean
# applies, retried-then-converged applies, terminal interruptions,
# state-write failures, and crashes.
DEFAULT_CHAOS_PROFILE: dict = {
    "faults": [
        {"fault": "api-429", "op": "create", "prob": 0.25, "max": 2},
        {"fault": "api-500", "op": "any", "prob": 0.10, "max": 2},
        {"fault": "tpu-stockout",
         "resource": "google_container_node_pool.*",
         "op": "create", "prob": 0.20, "max": 1},
        {"fault": "quota-exceeded", "op": "create", "prob": 0.10, "max": 1},
        {"fault": "preempted",
         "resource": "google_container_node_pool.*",
         "op": "create", "prob": 0.15, "max": 1},
        {"fault": "state-write-failed", "prob": 0.10, "max": 1},
        {"fault": "crash", "prob": 0.10, "max": 1},
    ],
}
