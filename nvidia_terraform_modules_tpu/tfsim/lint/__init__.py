# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""tfsim lint — pluggable static analysis above the ``validate`` floor.

See ``README.md`` in this directory for the rule catalog. Rule modules
are imported lazily by the engine (``validate`` imports ``engine`` for
the shared :class:`Finding`, and the core rules import validate back —
an eager package import would be a cycle).
"""

from .engine import (  # noqa: F401
    Finding,
    LintContext,
    Rule,
    RULES,
    exit_code,
    list_rules,
    run_lint,
)
