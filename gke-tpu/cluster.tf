# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Network, control plane, and CPU pool for the TPU cluster.
#
# Same L1-L3 capability as the gke/ sibling (VPC toggle, zonal/regional
# cluster, Workload Identity, autoscaled CPU pool) plus cluster-autoscaling /
# node-auto-provisioning limits for elastic TPU capacity (BASELINE config 5).

locals {
  create_vpc      = var.network.create
  network_name    = local.create_vpc ? google_compute_network.vpc[0].name : var.network.existing_network
  subnetwork_name = local.create_vpc ? google_compute_subnetwork.cluster[0].name : var.network.existing_subnetwork

  zonal            = length(var.node_zones) == 1
  cluster_location = local.zonal ? one(var.node_zones) : var.region
  pool_zones       = local.zonal ? null : var.node_zones

  node_oauth_scopes = [
    "https://www.googleapis.com/auth/logging.write",
    "https://www.googleapis.com/auth/monitoring",
    "https://www.googleapis.com/auth/devstorage.read_only",
  ]
}

resource "google_compute_network" "vpc" {
  count = local.create_vpc ? 1 : 0

  name                    = "${var.cluster_name}-net"
  project                 = var.project_id
  auto_create_subnetworks = false
}

resource "google_compute_subnetwork" "cluster" {
  count = local.create_vpc ? 1 : 0

  name                     = "${var.cluster_name}-subnet"
  project                  = var.project_id
  region                   = var.region
  network                  = google_compute_network.vpc[0].id
  ip_cidr_range            = var.network.subnet_cidr
  private_ip_google_access = true
}

data "google_container_engine_versions" "channel" {
  provider = google-beta

  project  = var.project_id
  location = local.cluster_location
}

resource "google_container_cluster" "this" {
  name     = var.cluster_name
  project  = var.project_id
  location = local.cluster_location

  network    = local.network_name
  subnetwork = local.subnetwork_name

  remove_default_node_pool = true
  initial_node_count       = 1

  deletion_protection = var.deletion_protection

  release_channel {
    channel = var.release_channel
  }

  workload_identity_config {
    workload_pool = "${var.project_id}.svc.id.goog"
  }

  # CMEK secrets-at-rest (reference EKS parity — see security.tf); the
  # provider default is Google-managed encryption, so the block only
  # renders when the operator opted in
  dynamic "database_encryption" {
    for_each = var.database_encryption.enabled ? [1] : []
    content {
      state    = "ENCRYPTED"
      key_name = local.secrets_kms_key
    }
  }

  # Google Groups for RBAC (reference AKS admin-groups parity)
  dynamic "authenticator_groups_config" {
    for_each = var.authenticator_security_group == null ? [] : [var.authenticator_security_group]
    content {
      security_group = authenticator_groups_config.value
    }
  }

  # observability floor for a TPU fleet: system metrics + Google Managed
  # Prometheus, so the smoketest/runtime telemetry (TPU_TELEMETRY_DIR
  # textfiles, tpu_healthprobe_* gauges via PodMonitoring) has a scrape
  # pipeline. The tpu-no-monitoring lint rule keeps this block honest.
  monitoring_config {
    enable_components = var.monitoring.enable_components

    managed_prometheus {
      enabled = var.monitoring.managed_prometheus
    }
  }

  dynamic "cluster_autoscaling" {
    for_each = var.node_auto_provisioning.enabled ? [1] : []
    content {
      enabled = true

      dynamic "resource_limits" {
        for_each = var.node_auto_provisioning.resource_limits
        content {
          resource_type = resource_limits.value.resource_type
          minimum       = resource_limits.value.minimum
          maximum       = resource_limits.value.maximum
        }
      }
    }
  }

  timeouts {
    create = "45m"
    update = "30m"
    delete = "45m"
  }

  # CMEK needs the service-agent grant BEFORE control-plane creation —
  # the key reference alone orders only against the key, and a cluster
  # racing ahead of the IAM member fails with CloudKMS access denied
  depends_on = [google_kms_crypto_key_iam_member.gke_agent]
}

resource "google_container_node_pool" "cpu" {
  name     = "${var.cluster_name}-cpu"
  project  = var.project_id
  cluster  = google_container_cluster.this.name
  location = local.cluster_location

  node_locations     = local.pool_zones
  initial_node_count = var.cpu_pool.initial_nodes

  autoscaling {
    min_node_count = var.cpu_pool.min_nodes
    max_node_count = var.cpu_pool.max_nodes
  }

  node_config {
    machine_type = var.cpu_pool.machine_type
    disk_size_gb = var.cpu_pool.disk_size_gb
    disk_type    = var.cpu_pool.disk_type
    spot         = var.cpu_pool.spot
    labels       = var.cpu_pool.labels

    oauth_scopes = local.node_oauth_scopes

    workload_metadata_config {
      mode = "GKE_METADATA"
    }
  }

  timeouts {
    create = "30m"
    update = "20m"
    delete = "30m"
  }
}
