# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Toolchain and provider pins for the GPU-parity GKE module.
#
# Capability parity: reference pins google 4.27 / google-beta 4.57 / helm 2.x
# and terraform >= 0.14 (/root/reference/gke/versions.tf:3-16). We pin the
# current major lines and a modern terraform floor so `optional()` object
# attributes and provider-defined functions are available.

terraform {
  required_version = ">= 1.5.0"

  required_providers {
    google = {
      source  = "hashicorp/google"
      version = "~> 6.8"
    }
    google-beta = {
      source  = "hashicorp/google-beta"
      version = "~> 6.8"
    }
    kubernetes = {
      source  = "hashicorp/kubernetes"
      version = "~> 2.32"
    }
    helm = {
      source  = "hashicorp/helm"
      version = "~> 2.15"
    }
  }
}
