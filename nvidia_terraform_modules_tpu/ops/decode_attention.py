# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Pallas TPU decode attention over an int8 KV cache: flash-decode with
in-kernel dequant, so int8 cache bytes are ALL that cross HBM per step.

The long-context serving step is KV-cache-bandwidth-bound: at [8, 3584+]
rows the bf16 cache is ~2.4 GB read per token while the (int8) weights
are 0.4 GB (``models/decode.py``). Quantising the cache to int8 halves
those bytes — but only if int8 is what actually crosses HBM. The jnp
path gets partway there by applying the scales AFTER the contractions
(``_cached_attention``), yet XLA still materialises converted operands
at long S (measured: int8 KV 2185 tok/s vs bf16 2132 at S=3616 — parity,
not the ~1.7× the byte math promises). This kernel removes the choice,
exactly as ``ops/int8_matmul.py`` does for the weights: cache tiles load
as int8 into VMEM, the int8→bf16 convert happens right before each MXU
dot, and the per-vector scales fold into the scores / probabilities —
``q·(k_q·s_k) = (q·k_q)·s_k`` and ``Σ_s p_s·(v_q·s_v)_s =
Σ_s (p_s·s_v,s)·v_q_s`` — which are [.., S] and tiny next to the
[.., S, D] cache.

Shape discipline (flash-decode recurrence, same VMEM model as
``ops/flash_attention.py``):

- grid (B, KV heads, S-blocks); the S sweep is innermost so the f32
  online-softmax state (m, l, acc) lives in VMEM scratch across it;
- the query is ONE token per batch row ([B, H, D], T=1 — the decode
  step; prefill and [1, k+1] verification keep the jnp path);
- GQA: queries reshape to [KV, rep, D] groups and contract against the
  un-repeated cache — scores are [rep, block_s] per tile;
- per-row positions: ``pos [B]`` (int32, broadcast to a lane-wide
  VMEM operand — vmap-safe) masks keys at
  ``s > pos`` — per-slot positions of the continuous-batching pool come
  for free; S-blocks entirely past ``pos`` are SKIPPED with ``pl.when``
  (no FLOPs, no DMA use), which also skips the ragged tail past S and
  keeps the first block always-live so the running max never sees a
  fully-dead update (the exp(-inf - -inf) NaN).

Reference analogue: none — the reference provisions serving infra and
never touches model bytes (``/root/reference/gke/README.md:50``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(pos_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
            m_scr, l_scr, acc_scr, *, scale, block_s, s_total, kv, rep):
    """One (batch row, S-block) tile: every KV head of the block.

    The cache tile keeps its native [block_s, KV, D] layout (a head-major
    relayout would cost a full-cache transpose per step in HBM); the
    per-head [rep, D]×[block_s, D] dots are tiny, but the op is
    cache-bandwidth-bound so MXU utilisation is irrelevant — what
    matters is that the tile is DMA'd once, as int8. Head slicing
    happens on the LANE axis (reshape to [block_s, KV·D], 128-multiple
    column slices), which Mosaic handles natively; per-head scores stack
    to [KV·rep, block_s] so the online-softmax state update stays one
    vectorised operation."""
    si, ns = pl.program_id(1), pl.num_programs(1)

    @pl.when(si == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[0, 0, 0]
    d = k_ref.shape[-1]
    hq = kv * rep

    def _per_head(xt):
        # [KV, bs] f32 (pre-transposed by the wrapper — an in-kernel
        # sublane↔lane transpose per tile was the kernel's single
        # biggest cost) → [KV·rep, bs]: sublane-repeat per query group
        return jnp.broadcast_to(xt[:, None, :],
                                (kv, rep, block_s)).reshape(hq, block_s)

    # the whole block is dead iff its first key is past this row's
    # position (pos < S always, so this also kills the ragged tail)
    @pl.when(si * block_s <= pos)
    def _live():
        # q arrives BLOCK-DIAGONAL [KV·rep, KV·D] (built per step in the
        # wrapper — 64 KB): one MXU dot computes every head's scores
        # against the tile in its native [bs, KV·D] layout, no per-head
        # loop, no head-major cache transpose
        qbd = q_ref[0]
        k2 = k_ref[0].astype(qbd.dtype).reshape(block_s, kv * d)
        v2 = v_ref[0].astype(qbd.dtype).reshape(block_s, kv * d)
        s = jax.lax.dot_general(
            qbd, k2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [KV·rep, bs]
        s = s * _per_head(ks_ref[0])                      # fold k scales
        s_idx = si * block_s + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where((s_idx <= pos) & (s_idx < s_total), s, NEG_INF)

        m_prev, l_prev = m_scr[:], l_scr[:]               # [KV·rep, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = (p * _per_head(vs_ref[0])).astype(qbd.dtype)  # fold v scales
        # one dot against the whole tile computes every (query-head ×
        # value-head) pair; the diagonal band — each query head with ITS
        # value head — is selected with a static one-hot reduce
        full = jax.lax.dot_general(
            pv, v2, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [KV·rep, KV·D]
        f3 = full.reshape(hq, kv, d)
        rowk = jax.lax.broadcasted_iota(jnp.int32, (hq, kv), 0) // rep
        colk = jax.lax.broadcasted_iota(jnp.int32, (hq, kv), 1)
        sel = (rowk == colk).astype(jnp.float32)[:, :, None]
        acc_scr[:] = acc_scr[:] * alpha + jnp.sum(f3 * sel, axis=1)
        m_scr[:] = m_new

    @pl.when(si == ns - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:]).astype(
            o_ref.dtype).reshape(o_ref.shape[1:])


def int8_kv_decode_attention(q, k_cache, k_scale, v_cache, v_scale, pos,
                             *, scale: float, block_s: int = 1024,
                             interpret: bool | None = None):
    """One decode step of attention over an int8 cache.

    ``q [B, H, D]`` (compute dtype) attends over ``k_cache``/``v_cache``
    ``[B, S, KV, D]`` int8 with per-vector f32 ``k_scale``/``v_scale``
    ``[B, S, KV]``; ``pos [B]`` int32 gives each row's query position
    (keys at ``s <= pos`` participate). Returns ``[B, H, D]`` in
    ``q.dtype``. ``H`` must be a multiple of ``KV``; ``D`` a lane
    multiple (128).
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, h, d = q.shape
    _, s_total, kv, _ = k_cache.shape
    rep = h // kv
    qg = q.reshape(b, kv, rep, d)
    pos = jnp.asarray(pos, jnp.int32).reshape(b)
    # S must tile EXACTLY: a ragged tail block would clamp its start
    # index and silently read earlier rows under the mask. init_cache
    # rounds int8 buffers to a 256-row grain; shrink to a divisor for
    # smaller/odd buffers and refuse when none exists.
    block_s = next(
        (bs for bs in (min(block_s, s_total), 256, 128, 64, 32, 16, 8)
         if bs % 8 == 0 and s_total % bs == 0), 0)
    if not block_s:
        raise ValueError(
            f"cache rows ({s_total}) need an 8-multiple block divisor "
            f"for the int8 decode kernel (init_cache rounds to 256)")
    ns = s_total // block_s

    # block-diagonal query: row k·rep+g carries head (k, g) in the d-band
    # of KV head k, so ONE dot against the [bs, KV·D]-shaped cache tile
    # contracts every head exactly (64 KB of h2d per step — negligible)
    eye = jnp.eye(kv, dtype=q.dtype)
    qbd = (qg[:, :, :, None, :] * eye[None, :, None, :, None]).reshape(
        b, kv * rep, kv * d)

    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, block_s=block_s,
                          s_total=s_total, kv=kv, rep=rep),
        grid=(b, ns),
        in_specs=[
            # per-row position as a [B, 1, 128] VMEM operand: the block's
            # trailing (1, 128) dims equal the array's, which stays legal
            # for ANY batch — including the extra leading dim jax.vmap
            # prepends when the serving pool batches this call (a rank-1
            # SMEM block breaks exactly there)
            pl.BlockSpec((1, 1, 128), lambda bi, si: (bi, 0, 0)),
            pl.BlockSpec((1, kv * rep, kv * d), lambda bi, si: (bi, 0, 0)),
            pl.BlockSpec((1, block_s, kv, d), lambda bi, si: (bi, si, 0, 0)),
            pl.BlockSpec((1, kv, block_s), lambda bi, si: (bi, 0, si)),
            pl.BlockSpec((1, block_s, kv, d), lambda bi, si: (bi, si, 0, 0)),
            pl.BlockSpec((1, kv, block_s), lambda bi, si: (bi, 0, si)),
        ],
        out_specs=pl.BlockSpec((1, kv * rep, d), lambda bi, si: (bi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, kv * rep, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((kv * rep, 1), jnp.float32),  # running max m
            pltpu.VMEM((kv * rep, 1), jnp.float32),  # running normaliser l
            pltpu.VMEM((kv * rep, d), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(jnp.broadcast_to(pos[:, None, None], (b, 1, 128)), qbd, k_cache,
      jnp.asarray(k_scale, jnp.float32).swapaxes(1, 2), v_cache,
      jnp.asarray(v_scale, jnp.float32).swapaxes(1, 2))
    return out.reshape(b, h, d)
