# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""graftlint — the Python-side binding of the shared rule engine.

The twin of ``tfsim/lint/engine.py``: one :class:`~.core.Registry`
instance, the rule decorator the ``rules_graft``/``lockgraph`` packs
register through, and :func:`run_graftlint` (build a
:class:`~.pysrc.PyContext`, run every enabled rule, filter, sort).

The rules encode the runtime conventions PRs 7–15 enforce by hand —
string-seeded RNG, no host sync in jitted wave loops, injected clocks,
classified-never-silent errors, lock-ordered shared state, no reuse of
donated buffers — so a violation fails CI before it reaches a chip.
"""

from __future__ import annotations

from typing import Optional

from .core import (  # noqa: F401  (re-exported shared API)
    SEVERITIES,
    Finding,
    Registry,
    Rule,
    exit_code,
)
from .pysrc import PyContext

REGISTRY = Registry(
    "graftlint",
    catalog_hint="(see `python -m nvidia_terraform_modules_tpu.analysis "
                 "-rules` for the catalog)")

RULES: dict[str, Rule] = REGISTRY.rules


def rule(id: str, *, severity: str, family: str, summary: str):
    return REGISTRY.rule(id, severity=severity, family=family,
                         summary=summary)


@REGISTRY.loader
def _ensure_rules_loaded() -> None:
    from . import lockgraph, rules_graft  # noqa: F401


def list_rules() -> list[Rule]:
    return REGISTRY.list()


def run_graftlint(root: str, rel_to: Optional[str] = None,
                  overrides: Optional[dict[str, str]] = None,
                  ctx: Optional[PyContext] = None) -> list[Finding]:
    """Run every enabled graft rule over the Python tree at ``root``.

    ``overrides`` maps rule id → severity (or ``"off"`` to disable).
    Returns findings sorted by (file, line, rule), suppressions applied.
    """
    overrides = overrides or {}
    # same contract as tfsim lint: a bad -severity flag is diagnosed
    # before any source loads
    REGISTRY.check_overrides(overrides)
    if ctx is None:
        ctx = PyContext(root, rel_to)
    return REGISTRY.run(ctx, overrides, ctx.suppressions(RULES))
