# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Fleet router: prefix-affinity multi-engine serving with SLO-aware
shedding and disaggregated prefill/decode.

One ``make_serve_engine`` is one chip's worth of traffic; the north
star is millions of users, which means a FLEET layer above the engine
(ROADMAP item 2). This module is that layer: ``N`` engine replicas —
threads on CPU, one engine per slice on chip — behind a router that
owns WHICH replica serves WHICH request and WHEN, driving each replica
through the engine's injectable :class:`..serving.AdmissionSource`
seam (never through private state):

- **Cache-affinity routing.** Each prompt's routing key is the head of
  its block-aligned ``PrefixIndex`` token-hash chain (the SAME
  ``H(root, first-kv_block-tokens)`` key the engine's prefix index
  matches on), consistent-hashed onto a virtual-node ring — so prompts
  sharing a template land on the replica that already holds that
  template's KV blocks, and the per-replica ``share_prefix`` index
  turns fleet-level placement into physical block reuse. The
  Gemma-on-TPU serving comparison (PAPERS.md) attributes its
  throughput wins to exactly this KV-reuse-aware placement layer. A
  LOAD-BALANCE OVERRIDE (``affinity_queue_bound``) reroutes to the
  least-loaded replica when the affinity target's predicted backlog at
  the request's arrival exceeds the bound — affinity must never become
  a hot-template hotspot.

- **SLO-aware admission.** Per-request deadlines (seconds from
  arrival; ``utils/traffic.slo_deadlines`` generates them from the
  same seeds as the arrival trace) drive LOAD SHEDDING at routing
  time: the router keeps a deterministic virtual clock per replica
  (predicted start = max(arrival, replica busy-until), predicted
  service = ``est_token_s × budget``) and sheds any request whose
  predicted completion would blow its deadline — admission control as
  a pure function of the trace, so shed decisions replay identically
  run to run (the bench determinism gate). Shed requests return
  ``None`` and are billed in ``last_stats["fleet"]``.

- **Cross-replica work stealing.** While replicas run, the router
  monitors queue depths: when one queue backs up (≥ 2 pending) while
  another sits empty, the backed-up queue's TAIL request moves over —
  tail-only so the head a replica may be mid-admitting is never taken.
  Tokens are schedule-invariant (the engine's exactness contract), so
  a steal can re-place a request freely; only placement stats change.

- **Disaggregated prefill/decode** (``disaggregate=True``).
  Podracer-style role split (PAPERS.md): ``prefill_workers`` replicas
  run prefill ONLY (the engine's ``prefill_session`` — compute-bound
  prompt-width matmuls, prefix sharing ACROSS requests per worker),
  and hand each finished prompt's KV to a decode worker with the PAGED
  BLOCK as the transfer unit (``paging.export_block_rows`` →
  ``kv_import`` admission → ``paging.import_block_rows``): an explicit
  pool-to-pool copy on CPU, and exactly the seam an ICI/DCN block
  transfer slots into on chip. Decode workers are
  bandwidth-bound wave loops that never pay a prefill. Routing
  affinity applies to the PREFILL side (that is where the prefix index
  lives); handoffs go to the least-loaded decode queue.

Exactness contract (the house gate, pinned in ``tests/test_fleet.py``):
the router is SCHEDULING, never a different model. A 1-replica fleet
bit-matches the bare engine per request; N-replica greedy outputs
bit-match solo decode whatever the placement, steals or preemptions;
disaggregated bit-matches colocated. Telemetry: one ``fleet_route``
span per request (args carry the chosen replica) on the SAME registry
the engines emit their ``serve_prefill``/``serve_request`` spans into,
so router and engine stitch on one Chrome-trace timeline;
``fleet_queue_depth``/``fleet_affinity_hit_frac`` gauges and
``fleet_shed_total``/``fleet_steal_total`` counters ride alongside.

Reference analogue: none — the reference provisions the node pools a
fleet like this runs on (SURVEY §2.6); this is the router those
``serve``-named slice pools front.
"""

from __future__ import annotations

import bisect
import hashlib
import random
import threading
import time
from typing import Any, Sequence

import numpy as np

from .burnin import BurnInConfig
from .paging import PrefixIndex, chain_chunks
from .serving import AdmissionSource, make_serve_engine

_ROUTINGS = ("affinity", "random")


def _blake_int(data: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


def affinity_key(tokens, block_size: int) -> bytes:
    """A prompt's routing key: the head of its block-aligned token-hash
    chain — ``PrefixIndex``'s OWN key for the first full ``block_size``
    chunk, so two prompts get the same routing key exactly when the
    engine's prefix index could share their first block. Prompts
    shorter than one block have nothing shareable; they key on their
    whole token string (spreading them is free)."""
    toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
    chunks = chain_chunks(toks, block_size)
    if chunks:
        return PrefixIndex._key(None, chunks[0])
    return hashlib.blake2b(
        ("short:" + ",".join(str(t) for t in toks)).encode(),
        digest_size=16).digest()


class HashRing:
    """Consistent-hash ring with virtual nodes: each target owns
    ``vnodes`` seeded points on a 64-bit ring; a key routes to the
    first point clockwise. Adding/removing a replica moves only
    ~1/N of the keyspace — the property that keeps template→replica
    placement (and therefore each replica's warm prefix index) stable
    across fleet resizes."""

    def __init__(self, n_targets: int, vnodes: int = 16):
        if n_targets < 1:
            raise ValueError(f"need >= 1 target, got {n_targets}")
        pts = sorted(
            (_blake_int(f"fleet-target-{t}-vnode-{v}".encode()), t)
            for t in range(n_targets) for v in range(vnodes))
        self._points = [p for p, _ in pts]
        self._targets = [t for _, t in pts]

    def target(self, key: bytes) -> int:
        i = bisect.bisect_right(self._points, _blake_int(key)) \
            % len(self._points)
        return self._targets[i]


class _FleetQueue(AdmissionSource):
    """One replica's admission stream, owned by the ROUTER: thread-safe
    (the serving engine polls from its run thread; the router primes,
    steals and closes from the monitor thread), arrival-ordered, with
    optional per-request kv-import payloads (the disaggregated
    handoff). ``exhausted()`` is closed-AND-empty — an open-but-empty
    queue keeps its engine's wave loop alive (``idle_wait`` polling)
    so a steal or a late handoff can still land."""

    def __init__(self, t0: float, poll_s: float, on_retire):
        self._lock = threading.Lock()
        self._pending: list[int] = []            # arrival-ascending
        self._arrival: dict[int, float] = {}
        self._payload: dict[int, Any] = {}
        self._closed = False
        self._claimed: int | None = None         # candidate in flight
        self.t0 = t0
        self.poll_s = poll_s
        self._on_retire = on_retire
        self.admitted = 0

    def _insort(self, req: int) -> None:
        bisect.insort(self._pending, req,
                      key=lambda r: (self._arrival[r], r))

    # ---- router-facing -------------------------------------------
    def add(self, req: int, arrival: float = 0.0, payload=None) -> None:
        with self._lock:
            self._arrival[req] = arrival
            if payload is not None:
                self._payload[req] = payload
            self._insort(req)

    def close(self) -> None:
        with self._lock:
            self._closed = True

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def steal_tail(self):
        """Remove and return ``(req, arrival, payload)`` for the
        LATEST-arrival pending request — only when ≥ 2 are pending and
        the tail is not the CLAIMED candidate (the one the replica may
        be mid-admitting between its ``candidate()`` and ``pop()``;
        normally the head, but a handoff ``add`` landing an
        earlier-arrival entry in the meantime can demote it to the
        tail — stealing it then would double-place the request and
        blow up the admitting engine's ``pop``)."""
        with self._lock:
            if len(self._pending) < 2 \
                    or self._pending[-1] == self._claimed:
                return None
            req = self._pending.pop()
            return (req, self._arrival[req],
                    self._payload.pop(req, None))

    # ---- engine-facing (AdmissionSource) -------------------------
    def candidate(self):
        now = time.monotonic() - self.t0
        with self._lock:
            if not self._pending:
                self._claimed = None
                return None
            head = self._pending[0]
            if self._arrival[head] > now:
                self._claimed = None
                return None
            # claim under the SAME lock the steal monitor takes: from
            # here until pop()/the next candidate(), the monitor will
            # not steal this request (a stale claim — admission held
            # for blocks — just shields one request until the next
            # poll of candidate(), never loses one)
            self._claimed = head
            return head

    def pop(self, req) -> None:
        with self._lock:
            self._pending.remove(req)
            if self._claimed == req:
                self._claimed = None
            self.admitted += 1

    def requeue(self, req) -> None:
        with self._lock:
            self._insort(req)

    def waiting(self) -> int:
        now = time.monotonic() - self.t0
        with self._lock:
            return sum(1 for r in self._pending
                       if self._arrival[r] <= now)

    def exhausted(self) -> bool:
        with self._lock:
            return self._closed and not self._pending

    def idle_wait(self) -> None:
        now = time.monotonic() - self.t0
        with self._lock:
            nxt = (self._arrival[self._pending[0]]
                   if self._pending else None)
        if nxt is not None and nxt > now:
            time.sleep(min(nxt - now, self.poll_s))
        else:
            time.sleep(self.poll_s)

    def wait_s(self, req) -> float:
        return max(0.0, time.monotonic() - self.t0
                   - self._arrival.get(req, 0.0))

    def kv_import(self, req):
        return self._payload.get(req)

    def retired(self, req, tokens: int) -> None:
        with self._lock:
            self._payload.pop(req, None)
        self._on_retire(req, tokens)


def _take_next(q: _FleetQueue):
    """Blocking pull for the prefill-worker loop (the decode side's
    engine loop does its own polling through the interface)."""
    while True:
        req = q.candidate()
        if req is not None:
            q.pop(req)
            return req
        if q.exhausted():
            return None
        q.idle_wait()


def make_fleet(params, cfg: BurnInConfig, *, max_len: int,
               replicas: int = 2, routing: str = "affinity",
               affinity_queue_bound: int | None = None,
               disaggregate: bool = False, prefill_workers: int = 1,
               steal: bool = True, steal_poll_s: float = 0.002,
               est_token_s: float | None = None,
               telemetry=None, route_seed: int = 0,
               **engine_kw):
    """Build the fleet: ``replicas`` serve engines behind the router.

    Returns ``fleet(prompts, n_new, *, slots=4, eos_id=None, rng=None,
    arrivals=None, deadlines=None, kv_blocks=None) → list`` — one
    token array per request in request order, ``None`` where the SLO
    admission shed. After each call ``fleet.last_stats`` carries the
    engines' per-replica stats (``"replica_stats"``) plus the router's
    own ``"fleet"`` record: per-replica request counts / occupancy /
    waves / KV peaks, the affinity hit fraction realised by the
    replicas' prefix indexes, shed and steal counts, and deadline
    attainment (fraction of served deadline-carrying requests that
    finished inside their deadline, wall clock).

    ``routing="affinity"`` (default) consistent-hashes each prompt's
    first-block token-hash chain key onto the replica ring (see
    :func:`affinity_key`); ``"random"`` places seeded-uniformly — the
    A/B baseline ``bench.py section_serve_fleet`` compares hit
    fractions against. ``affinity_queue_bound`` caps how deep an
    affinity target's predicted backlog may grow before the router
    overrides to the least-loaded replica.

    ``deadlines`` (per request, seconds from arrival) turn on SLO
    admission: the router's deterministic virtual clock predicts each
    request's completion (service ≈ ``est_token_s`` × its ``n_new``
    budget — calibrate ``est_token_s`` from a measured run; it is
    required when deadlines are given) and SHEDS requests whose
    prediction blows the deadline, before any device work.

    ``disaggregate=True`` splits the ``replicas`` into
    ``prefill_workers`` prefill-only workers and the rest decode-only
    workers: prefill workers run ``prefill_session`` loops (affinity
    routing applies to THEM — the prefix index lives with prefill) and
    hand finished prompts' KV blocks to the least-loaded decode
    worker's queue as ``kv_import`` payloads. Greedy only (the handoff
    carries a picked first token).

    ``**engine_kw`` passes through to every ``make_serve_engine``
    (``kv_block``, ``share_prefix``, ``cache_dtype``, ``lazy_growth``,
    ``paged_kernel``, ``sampler``, …). Note an engine driven through an
    injected admission source never consults its own ``policy`` — the
    router IS the policy. The fleet's telemetry registry (``telemetry=``,
    default the process registry) is shared with every engine, so
    ``fleet_route`` spans and the engines' serve spans land on ONE
    timeline.
    """
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1, got {replicas}")
    if routing not in _ROUTINGS:
        raise ValueError(f"unknown routing {routing!r}: "
                         f"use {' | '.join(_ROUTINGS)}")
    if affinity_queue_bound is not None and affinity_queue_bound < 1:
        raise ValueError(f"affinity_queue_bound must be >= 1, got "
                         f"{affinity_queue_bound}")
    if est_token_s is not None and est_token_s <= 0:
        raise ValueError(f"est_token_s must be > 0, got {est_token_s}")
    if disaggregate:
        if replicas < 2:
            raise ValueError(
                "disaggregate=True needs >= 2 replicas (at least one "
                "prefill worker AND one decode worker)")
        if not 1 <= prefill_workers <= replicas - 1:
            raise ValueError(
                f"prefill_workers must be in [1, replicas-1] = "
                f"[1, {replicas - 1}], got {prefill_workers}")
        if engine_kw.get("sampler") is not None:
            raise ValueError(
                "disaggregated serving is greedy-only: the prefill "
                "handoff carries a greedily picked first token")
        for k in ("spec_k", "prefix", "prefill_chunk"):
            if engine_kw.get(k) is not None:
                raise ValueError(
                    f"disaggregate=True does not compose with {k} "
                    f"(see prefill_session)")
    from ..telemetry import get_registry

    reg = telemetry if telemetry is not None else get_registry()
    kv_block = engine_kw.get("kv_block", 16)
    n_pre = prefill_workers if disaggregate else 0
    n_dec = replicas - n_pre
    # every engine shares the fleet's registry so router + engine spans
    # stitch on one timeline; engines are separate objects on purpose —
    # separate pools, separate step caches, no cross-thread state
    dec_engines = [make_serve_engine(params, cfg, max_len=max_len,
                                     telemetry=reg, **engine_kw)
                   for _ in range(n_dec)]
    pre_engines = [make_serve_engine(params, cfg, max_len=max_len,
                                     telemetry=reg, **engine_kw)
                   for _ in range(n_pre)]
    ring = HashRing(n_pre if disaggregate else n_dec)
    if reg.enabled:
        _g_depth = reg.gauge("fleet_queue_depth")
        _g_hitf = reg.gauge("fleet_affinity_hit_frac")
        _c_shed = reg.counter("fleet_shed_total")
        _c_steal = reg.counter("fleet_steal_total")

    def _plan(prompts, budgets, arrivals, deadlines):
        """Deterministic routing + shed plan — a pure function of the
        trace (prompt tokens, arrivals, budgets, deadlines) and the
        route seed, so shed fractions and placements replay exactly.
        The virtual clock models each TARGET as a serial server at
        ``est_token_s`` per budgeted token: coarse on purpose — it is
        admission control (shed what cannot possibly meet its
        deadline), not a simulator; work stealing repairs what the
        model mispredicts."""
        n_targets = n_pre if disaggregate else n_dec
        rnd = random.Random(f"fleet-route-{route_seed}")
        busy_until = [0.0] * n_targets
        finishes: list[list[float]] = [[] for _ in range(n_targets)]
        plan = []                        # (req, target, by_affinity)
        shed = []
        for req in range(len(prompts)):
            a = arrivals[req] if arrivals is not None else 0.0
            if routing == "affinity":
                t_aff = ring.target(affinity_key(prompts[req], kv_block))
            else:
                t_aff = rnd.randrange(n_targets)
            t, by_aff = t_aff, routing == "affinity"
            if affinity_queue_bound is not None:
                backlog = sum(1 for f in finishes[t_aff] if f > a)
                if backlog >= affinity_queue_bound:
                    t = min(range(n_targets),
                            key=lambda j: (max(busy_until[j], a), j))
                    by_aff = by_aff and t == t_aff
            start = max(a, busy_until[t])
            finish = start + (est_token_s or 0.0) * budgets[req]
            if deadlines is not None and finish - a > deadlines[req]:
                shed.append(req)
                continue
            busy_until[t] = finish
            finishes[t].append(finish)
            plan.append((req, t, by_aff))
        return plan, shed

    def fleet(prompts: Sequence[Any], n_new, *, slots: int = 4,
              eos_id: int | None = None, rng=None, arrivals=None,
              deadlines=None, kv_blocks: int | None = None) -> list:
        fleet.last_stats = None
        n = len(prompts)
        if n == 0:
            return []
        budgets = ([n_new] * n if isinstance(n_new, int)
                   else [int(x) for x in n_new])
        if len(budgets) != n:
            raise ValueError(
                f"per-request n_new has {len(budgets)} entries for "
                f"{n} prompts")
        if arrivals is not None:
            arrivals = [float(a) for a in arrivals]
            if len(arrivals) != n:
                raise ValueError(
                    f"arrivals has {len(arrivals)} entries for "
                    f"{n} prompts")
        if deadlines is not None:
            deadlines = [float(d) for d in deadlines]
            if len(deadlines) != n:
                raise ValueError(
                    f"deadlines has {len(deadlines)} entries for "
                    f"{n} prompts")
            if est_token_s is None:
                raise ValueError(
                    "SLO shedding needs est_token_s (predicted "
                    "service per budgeted token) — calibrate it from "
                    "a measured run of this config")

        plan, shed = _plan(prompts, budgets, arrivals, deadlines)
        t0 = time.monotonic()
        retire_at: dict[int, float] = {}
        retire_tok: dict[int, int] = {}
        r_lock = threading.Lock()

        def on_retire(req, tokens):
            with r_lock:
                retire_at[req] = time.monotonic() - t0
                retire_tok[req] = tokens

        dec_queues = [_FleetQueue(t0, steal_poll_s, on_retire)
                      for _ in range(n_dec)]
        pre_queues = [_FleetQueue(t0, steal_poll_s, on_retire)
                      for _ in range(n_pre)]
        routed_to: dict[int, str] = {}
        by_aff_n = 0
        for req, t, by_aff in plan:
            a = arrivals[req] if arrivals is not None else 0.0
            label = (f"prefill-{t}" if disaggregate else f"replica-{t}")
            (pre_queues if disaggregate else dec_queues)[t].add(req, a)
            routed_to[req] = label
            by_aff_n += by_aff
            if reg.enabled:
                tc = reg.clock()
                reg.emit_span("fleet_route", tc, tc, request=req,
                              replica=label, affinity=bool(by_aff),
                              shed=False)
        for req in shed:
            if reg.enabled:
                tc = reg.clock()
                reg.emit_span("fleet_route", tc, tc, request=req,
                              replica=None, affinity=False, shed=True)
        if reg.enabled and shed:
            _c_shed.inc(len(shed))
        for q in pre_queues:
            q.close()                    # routing is final for prefill

        sessions: list[Any] = [None] * n_pre
        results: list[Any] = [None] * n_dec
        errors: list[tuple] = []
        stolen = [0]

        def _abort_all():
            for q in pre_queues + dec_queues:
                q.close()

        def dec_worker(i):
            try:
                results[i] = dec_engines[i](
                    prompts, budgets, slots=slots, eos_id=eos_id,
                    rng=rng, kv_blocks=kv_blocks,
                    admission=dec_queues[i])
            except Exception as exc:     # noqa: BLE001 — re-raised below
                errors.append((f"decode-{i}", exc))
                _abort_all()

        def pre_worker(i):
            try:
                sessions[i] = pre_engines[i].prefill_session()
                while True:
                    req = _take_next(pre_queues[i])
                    if req is None:
                        break
                    payload = sessions[i].prefill(prompts[req])
                    # least-loaded decode queue (tie → lowest index):
                    # decode placement is free — the payload carries
                    # everything, affinity already paid off at prefill
                    j = min(range(n_dec),
                            key=lambda d: (dec_queues[d].pending_count(),
                                           d))
                    a = (arrivals[req] if arrivals is not None else 0.0)
                    dec_queues[j].add(req, a, payload)
                    if reg.enabled:
                        tc = reg.clock()
                        reg.emit_span("fleet_route", tc, tc,
                                      request=req,
                                      replica=f"decode-{j}",
                                      affinity=False, shed=False,
                                      handoff=True)
            except Exception as exc:     # noqa: BLE001 — re-raised below
                errors.append((f"prefill-{i}", exc))
                _abort_all()
            finally:
                if sessions[i] is not None:
                    sessions[i].close()

        pre_threads = [threading.Thread(target=pre_worker, args=(i,),
                                        daemon=True)
                       for i in range(n_pre)]
        dec_threads = [threading.Thread(target=dec_worker, args=(i,),
                                        daemon=True)
                       for i in range(n_dec)]
        for th in pre_threads + dec_threads:
            th.start()

        # ---- the router's monitor loop (this thread): queue-depth
        # gauge, work stealing, and closure once no add can ever come
        while any(th.is_alive() for th in dec_threads):
            depths = [q.pending_count() for q in dec_queues]
            if reg.enabled:
                _g_depth.set(sum(depths)
                             + sum(q.pending_count()
                                   for q in pre_queues))
            adds_done = not any(th.is_alive() for th in pre_threads)
            if adds_done and sum(depths) == 0:
                for q in dec_queues:
                    q.close()
                break
            if steal and n_dec > 1:
                receivers = [i for i, d in enumerate(depths) if d == 0]
                donor = max(range(n_dec), key=lambda i: depths[i])
                if receivers and depths[donor] >= 2 \
                        and donor not in receivers:
                    got = dec_queues[donor].steal_tail()
                    if got is not None:
                        req, a, payload = got
                        dec_queues[receivers[0]].add(req, a, payload)
                        routed_to[req] = f"stolen->{receivers[0]}"
                        stolen[0] += 1
                        if reg.enabled:
                            _c_steal.inc()
            time.sleep(steal_poll_s)
        for th in pre_threads + dec_threads:
            th.join()
        if errors:
            where, exc = errors[0]
            raise RuntimeError(
                f"fleet worker {where} failed: {exc}") from exc

        merged: dict[int, Any] = {}
        for r in results:
            merged.update(r or {})
        missing = set(range(n)) - set(shed) - set(merged)
        if missing:
            # a lost request is a router bug, never silent truncation
            raise RuntimeError(
                f"fleet lost requests {sorted(missing)} — served "
                f"{len(merged)}, shed {len(shed)} of {n}")

        # ---- stats -----------------------------------------------
        per_replica = []
        hit_b = prompt_b = saved = 0
        for i, e in enumerate(dec_engines):
            st = e.last_stats
            per_replica.append({
                "role": "decode", "replica": f"decode-{i}"
                if disaggregate else f"replica-{i}",
                "requests": st["requests"], "waves": st["waves"],
                "occupancy": st["sched"]["mean_live_requests"],
                "kv_peak_blocks": st["kv"]["high_water"],
                "preempted": st["sched"]["preempted"],
            })
            hit_b += st["prefix"]["hit_blocks"]
            prompt_b += st["prefix"]["prompt_blocks"]
            saved += st["prefix"]["tokens_saved"]
        for i, s in enumerate(sessions):
            if s is None:
                continue
            per_replica.append({
                "role": "prefill", "replica": f"prefill-{i}",
                "requests": s.stats["requests"], "waves": None,
                "occupancy": None, "kv_peak_blocks": s.alloc.high_water,
                "preempted": 0,
            })
            hit_b += s.stats["hit_blocks"]
            prompt_b += s.stats["prompt_blocks"]
            saved += s.stats["tokens_saved"]
        hit_frac = round(hit_b / max(prompt_b, 1), 4)

        met = with_dl = 0
        goodput_tokens = 0
        lat_ms: list[float] = []         # arrival → completion, per req
        for req in merged:
            tok = retire_tok.get(req, int(merged[req].shape[0]))
            a = arrivals[req] if arrivals is not None else 0.0
            done = retire_at.get(req)
            if done is not None:
                lat_ms.append(max(0.0, done - a) * 1e3)
            if deadlines is None:
                goodput_tokens += tok
                continue
            with_dl += 1
            ok = (done if done is not None else float("inf")) - a \
                <= deadlines[req]
            met += ok
            if ok:
                goodput_tokens += tok
        lat_ms.sort()

        def _q(p):
            return (round(lat_ms[min(len(lat_ms) - 1,
                                     int(p * len(lat_ms)))], 3)
                    if lat_ms else None)
        if reg.enabled:
            _g_hitf.set(hit_frac)
            _g_depth.set(0)

        fleet.last_stats = {
            "fleet": {
                "replicas": replicas,
                "mode": ("disaggregated" if disaggregate
                         else "colocated"),
                "prefill_workers": n_pre,
                "routing": routing,
                "requests": n,
                "served": len(merged),
                "shed": len(shed),
                "shed_requests": sorted(shed),
                "stolen": stolen[0],
                "affinity_routed_frac": round(
                    by_aff_n / max(len(plan), 1), 4),
                "affinity_hit_blocks": hit_b,
                "affinity_hit_frac": hit_frac,
                "prefill_tokens_saved": saved,
                "deadline_attainment": (round(met / with_dl, 4)
                                        if with_dl else None),
                "goodput_tokens": goodput_tokens,
                # arrival → completion (the user's clock: router queue
                # time INCLUDED, unlike the per-engine latency record
                # which starts at admission)
                "latency_ms": {"p50": _q(0.5), "p99": _q(0.99),
                               "max": (round(lat_ms[-1], 3)
                                       if lat_ms else None)},
                "per_replica": per_replica,
                "routed_to": routed_to,
            },
            "replica_stats": [e.last_stats for e in dec_engines],
        }
        out: list[Any] = [None] * n
        for req, toks in merged.items():
            out[req] = toks
        return out

    fleet.last_stats = None
    return fleet
