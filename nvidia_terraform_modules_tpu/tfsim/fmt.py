# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Canonical HCL formatting: the offline stand-in for ``terraform fmt``.

The reference's pre-checkin gate is ``terraform fmt`` run by hand
(``/root/reference/CONTRIBUTING.md:12``); with no terraform binary in the
test environment, this module reimplements the formatter's observable
behaviour so CI can enforce it (``check_text``) and fix it (``format_text``):

- two-space indentation derived from bracket structure, one level per line
  that opens a group (hclwrite's rule: ``object({`` is ONE level, not two);
- ``=`` alignment across runs of consecutive single-line attributes;
- single space around ``=``; no trailing whitespace; tabs → spaces;
- runs of blank lines collapsed to one; exactly one trailing newline;
- heredoc bodies and block-comment interiors left verbatim.

Like tfsim itself, it is a deliberate subset: it handles the HCL this repo
writes and fails loudly (via the parser) on anything it cannot lex.
"""

from __future__ import annotations

import dataclasses
import re

_OPENERS = "([{"
_CLOSERS = ")]}"
_MATCH = {")": "(", "]": "[", "}": "{"}

# attribute line: `name = expr` (not ==, =>, <=, >=, !=)
_ATTR_RE = re.compile(
    r"^(?P<name>[A-Za-z_][A-Za-z0-9_-]*)\s*=(?![=>])\s*(?P<value>.*)$"
)


@dataclasses.dataclass
class _Line:
    raw: str                 # original text, no trailing newline
    verbatim: bool = False   # heredoc body / block-comment interior: untouched
    blank: bool = False
    # delimiters outside strings/comments, in order
    delims: str = ""
    # True if the line *starts* (after indent) with a closer
    heredoc_open: bool = False


def _scan(text: str) -> list[_Line]:
    """Split source into lines annotated with structural facts.

    A single forward scan tracks string / interpolation / comment / heredoc
    state so delimiters inside them are not mistaken for structure.
    """
    lines = [_Line(raw=l) for l in text.split("\n")]
    i, n = 0, len(text)
    lineno = 0
    # string-scanner context stack, as in lexer.py: str / interp / brace
    stack: list[str] = []
    in_block_comment = False
    heredoc_marker: str | None = None
    line_start = True

    def cur(idx: int) -> _Line:
        return lines[idx]

    while i < n:
        c = text[i]
        if c == "\n":
            # blank lines inside heredocs / block comments never reach the
            # branches below — mark them verbatim here so the blank-run
            # collapse can't eat heredoc content
            if (heredoc_marker is not None or in_block_comment) and (
                lines[lineno].raw.strip() == ""
            ):
                lines[lineno].verbatim = True
            lineno += 1
            i += 1
            line_start = True
            continue
        ln = cur(lineno)

        if heredoc_marker is not None:
            ln.verbatim = True
            if line_start and ln.raw.strip() == heredoc_marker:
                heredoc_marker = None
                ln.verbatim = True  # the end marker keeps its own indent
            # skip to end of line
            eol = text.find("\n", i)
            i = n if eol < 0 else eol
            continue

        if in_block_comment:
            end = text.find("*/", i)
            eol = text.find("\n", i)
            if end >= 0 and (eol < 0 or end < eol):
                in_block_comment = False
                if line_start:
                    # line began inside the comment: keep it verbatim even
                    # though the comment closes here
                    ln.verbatim = True
                i = end + 2
            else:
                if not line_start or ln.raw.strip() != "":
                    ln.verbatim = ln.verbatim or not line_start
                if line_start:
                    ln.verbatim = True
                i = n if eol < 0 else eol
            continue

        if stack:
            # inside a (possibly interpolated) string
            top = stack[-1]
            if top == "str":
                if c == "\\":
                    i += 2
                    continue
                if text.startswith("${", i) or text.startswith("%{", i):
                    stack.append("interp")
                    i += 2
                    continue
                if c == '"':
                    stack.pop()
            else:
                if c == '"':
                    stack.append("str")
                elif c == "{":
                    stack.append("brace")
                elif c == "}":
                    stack.pop()
            i += 1
            line_start = False
            continue

        # ---- outside any string ----
        if c == '"':
            stack.append("str")
            i += 1
            line_start = False
            continue
        if c == "#" or text.startswith("//", i):
            eol = text.find("\n", i)
            i = n if eol < 0 else eol
            continue
        if text.startswith("/*", i):
            in_block_comment = True
            i += 2
            line_start = False
            continue
        if text.startswith("<<", i):
            j = i + 2
            if j < n and text[j] in "-~":
                j += 1
            m = re.match(r"[A-Za-z_][A-Za-z0-9_]*", text[j:])
            if m:
                heredoc_marker = m.group(0)
                ln.heredoc_open = True
                eol = text.find("\n", i)
                i = n if eol < 0 else eol
                continue
            i += 2
            continue
        if c in _OPENERS or c in _CLOSERS:
            ln.delims += c
        i += 1
        line_start = False

    for ln in lines:
        ln.blank = (not ln.verbatim) and ln.raw.strip() == ""
    return lines


def _reindent(lines: list[_Line]) -> list[str]:
    """Recompute indentation from bracket structure (2 spaces per level)."""
    out: list[str] = []
    # stack entries = number of delimiters opened by one source line
    stack: list[int] = []
    for ln in lines:
        if ln.verbatim:
            out.append(ln.raw)
            continue
        if ln.blank:
            out.append("")
            continue
        content = ln.raw.strip()
        # a line that starts with a closer sits at its opener's level
        # (hclwrite's rule — even when it only partially closes the group,
        # e.g. `})), [])` under `optional(list(object({`)
        dedented = content[:1] in _CLOSERS and stack
        level = len(stack) - 1 if dedented else len(stack)
        opened = 0
        for d in ln.delims:
            if d in _OPENERS:
                opened += 1
            else:
                if opened > 0:
                    opened -= 1
                elif stack:
                    stack[-1] -= 1
                    if stack[-1] == 0:
                        stack.pop()
        if opened > 0:
            stack.append(opened)
        out.append("  " * level + content)
    return out


def _align(lines: list[str], scanned: list[_Line]) -> list[str]:
    """Align ``=`` across runs of consecutive single-line attributes."""
    out = list(lines)
    run: list[int] = []

    def flush():
        # a run of one still gets `name = value` spacing (width == len(name));
        # runs of two or more additionally align their `=` columns
        if run:
            parsed = []
            for idx in run:
                indent = len(out[idx]) - len(out[idx].lstrip())
                m = _ATTR_RE.match(out[idx].strip())
                parsed.append((idx, indent, m.group("name"), m.group("value")))
            width = max(len(name) for _, _, name, _ in parsed)
            for idx, indent, name, value in parsed:
                out[idx] = f"{' ' * indent}{name}{' ' * (width - len(name))} = {value}"
        run.clear()

    prev_indent = None
    for idx, text in enumerate(out):
        if scanned[idx].verbatim:
            flush()
            prev_indent = None
            continue
        stripped = text.strip()
        indent = len(text) - len(text.lstrip())
        m = _ATTR_RE.match(stripped)
        # a run member must be a one-line attribute (balanced delimiters,
        # no heredoc opener) at the same indent as the rest of the run
        one_line = (
            m is not None
            and not scanned[idx].heredoc_open
            and _balanced(scanned[idx].delims)
        )
        if one_line and (prev_indent is None or indent == prev_indent or not run):
            if run and indent != prev_indent:
                flush()
            run.append(idx)
            prev_indent = indent
        else:
            flush()
            prev_indent = None
    flush()
    return out


def _balanced(delims: str) -> bool:
    stack: list[str] = []
    for d in delims:
        if d in _OPENERS:
            stack.append(d)
        else:
            if not stack or stack[-1] != _MATCH[d]:
                return False
            stack.pop()
    return not stack


def format_text(text: str) -> str:
    """Return the canonical form of ``text``."""
    scanned = _scan(text)
    indented = _reindent(scanned)
    aligned = _align(indented, scanned)
    # collapse blank-line runs (outside verbatim regions), drop leading blanks
    out: list[str] = []
    blank_pending = False
    for ln, meta in zip(aligned, scanned):
        if not meta.verbatim and ln.strip() == "":
            blank_pending = bool(out)
            continue
        if blank_pending:
            out.append("")
            blank_pending = False
        out.append(ln if meta.verbatim else ln.rstrip())
    return "\n".join(out) + "\n"


@dataclasses.dataclass
class FmtDiff:
    path: str
    line: int       # 1-based line in the ORIGINAL file
    got: str
    want: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: not canonically formatted\n"
                f"  got:  {self.got!r}\n  want: {self.want!r}")


def check_text(text: str, path: str = "<hcl>") -> list[FmtDiff]:
    """Diff ``text`` against its canonical form; empty list = already canonical."""
    formatted = format_text(text)
    if formatted == text:
        return []
    import difflib

    diffs: list[FmtDiff] = []
    orig = text.split("\n")
    new = formatted.split("\n")
    sm = difflib.SequenceMatcher(a=orig, b=new, autojunk=False)
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag == "equal":
            continue
        got = orig[i1] if i1 < len(orig) else ""
        want = new[j1] if j1 < len(new) else ""
        diffs.append(FmtDiff(path, i1 + 1, got, want))
    return diffs


def check_file(path: str) -> list[FmtDiff]:
    with open(path, encoding="utf-8") as f:
        return check_text(f.read(), path)


def format_file(path: str, write: bool = False) -> bool:
    """Format one file. Returns True if it was already canonical."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    formatted = format_text(text)
    if formatted == text:
        return True
    if write:
        with open(path, "w", encoding="utf-8") as f:
            f.write(formatted)
    return False


def main(argv: list[str] | None = None) -> int:
    """CLI: ``python -m nvidia_terraform_modules_tpu.tfsim.fmt [-check] PATH...``

    Mirrors ``terraform fmt``: rewrites files to canonical form by default;
    ``-check`` only reports (exit 3 on drift, like terraform's ``-check``).
    Directory arguments are searched recursively for ``*.tf``.
    """
    import argparse
    import glob as _glob
    import os
    import sys

    ap = argparse.ArgumentParser(prog="tfsim fmt")
    ap.add_argument("-check", action="store_true",
                    help="report files that are not canonically formatted")
    ap.add_argument("paths", nargs="+")
    args = ap.parse_args(argv)

    files: list[str] = []
    for p in args.paths:
        if os.path.isdir(p):
            files += sorted(_glob.glob(os.path.join(p, "**", "*.tf"),
                                       recursive=True))
        else:
            files.append(p)

    drift = 0
    for path in files:
        if args.check:
            for d in check_file(path):
                print(d, file=sys.stderr)
                drift += 1
        elif not format_file(path, write=True):
            print(path)
            drift += 1
    return 3 if (args.check and drift) else 0


if __name__ == "__main__":
    raise SystemExit(main())
