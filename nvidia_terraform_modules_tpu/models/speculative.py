# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Prompt-lookup speculative decoding: draft free tokens, verify in one pass.

Greedy decode runs one HBM-bound forward per token (``models/decode.py``).
Speculation converts some of that serial chain into parallel verification:
draft ``k`` candidate tokens cheaply, run ONE cached forward over all of
them (the same weights-read cost as a single step — the decode regime is
weight-bandwidth-bound, so verifying k+1 positions costs ~one step), and
accept the longest prefix the model itself would have produced.

The draft source here is **prompt lookup** (n-gram continuation): find the
most recent earlier occurrence of the current bigram in the generated
context and propose the tokens that followed it. No draft model, no extra
weights — the lever targets the structured/repetitive decoding real
serving sees (code, retrieval-augmented text, templated output); on
incompressible token streams acceptance just drops toward zero and the
loop degrades to ~plain greedy decode, never below it by more than the
(k)-position verification overhead.

**Exactness guarantee**: output EQUALS ``greedy_decode`` token for token
*up to backend matmul-tiling numerics*, whatever the drafts are —
acceptance tests argmax equality position by position, and the first
mismatch is replaced by the verifier's own argmax (the token plain greedy
would have emitted given equal logits). The acceptance logic itself is
exact; the caveat is that the ``[1, k+1]`` verification forward can tile
its matmuls differently from greedy's ``T=1`` step path, so on bf16 TPU a
near-tie argmax may resolve differently (verified bit-exact on CPU f32 in
``tests/test_speculative.py``). The cache
rolls back by resetting ``pos`` only: rows past ``pos`` are causally
masked out of every later attention and are overwritten in place when
real decoding reaches them (``lax.dynamic_update_slice`` at the same
offsets), so no buffer surgery is needed.

TPU-first shape discipline: the whole generate loop is ONE
``lax.while_loop`` with static shapes — a fixed ``[1, max_len]`` context
buffer, ``k`` static, every verification a ``[1, k+1]`` cached forward —
so speculation compiles once like everything else. Batch is 1 by design:
speculation is a LATENCY lever, and per-row acceptance divergence under
batching would force per-row cache offsets (a different design). The
THROUGHPUT variant lives in ``models/serving.py::make_spec_step``: the
same :func:`accept_drafts` core batched over the paged slot pool, with
per-slot positions carrying the cache offsets this loop avoids and a
per-k-token growth boundary so it composes with the engine's lazy block
growth and cross-request prefix sharing.

Reference analogue: none — the reference provisions serving
infrastructure and never touches model bytes (SURVEY §2.6).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..parallel.sharding import ShardingRules
from .burnin import BurnInConfig
from .decode import _select_prefill_impl, forward_cached, init_cache


def _ngram_draft(ctx, cur_len, k: int, vocab: int):
    """Draft ``k`` tokens by bigram lookup in ``ctx [L]`` (valid ``cur_len``).

    Finds the LATEST position ``i < cur_len - 2`` with
    ``ctx[i:i+2] == ctx[cur_len-2:cur_len]`` and proposes
    ``ctx[i+2 : i+2+k]``. No match → repeat the last token (a draft that
    will usually be rejected — correctness never depends on draft
    quality). All static shapes; runs inside the while_loop."""
    L = ctx.shape[0]
    idx = jnp.arange(L)
    a = ctx
    b = jnp.roll(ctx, -1)                       # b[i] = ctx[i+1]
    suf0 = ctx[jnp.maximum(cur_len - 2, 0)]
    suf1 = ctx[jnp.maximum(cur_len - 1, 0)]
    match = (a == suf0) & (b == suf1) & (idx + 2 < cur_len)
    pos = jnp.max(jnp.where(match, idx, -1))
    start = jnp.where(pos >= 0, pos + 2, jnp.maximum(cur_len - 1, 0))
    gather = jnp.clip(start + jnp.arange(k), 0, L - 1)
    return jnp.clip(ctx[gather], 0, vocab - 1)


def accept_drafts(draft, preds):
    """The speculation acceptance core, shared by the solo loop below
    and the serving engine's per-slot verification step
    (``models/serving.py``): accept the longest prefix of ``draft``
    ``[k]`` agreeing with the model's own argmax chain ``preds``
    ``[k+1]``, and splice the model's next token (the correction at the
    first mismatch, the continuation when everything agreed) in behind
    it. Returns ``(new_toks [k+1], n_acc)`` — callers apply their own
    emission cap (n_new budget, eos windows). One definition so the
    solo and continuous-batching paths can never diverge on what
    "accepted" means."""
    agree = draft == preds[:-1]
    n_acc = jnp.argmin(jnp.concatenate(
        [agree, jnp.array([False])]).astype(jnp.int32))   # 0..k
    new_toks = jnp.concatenate([draft, jnp.zeros((1,), draft.dtype)])
    new_toks = new_toks.at[n_acc].set(preds[n_acc])
    return new_toks, n_acc


def speculative_greedy_decode(params, prompt, n_new: int,
                              cfg: BurnInConfig,
                              rules: ShardingRules | None = None,
                              k: int = 4, max_len: int | None = None,
                              prefill: str = "auto"):
    """Greedy generation via prompt-lookup speculation.

    Returns ``(tokens [1, n_new], steps)`` where ``steps`` is the number
    of verification forwards actually run — ``n_new / steps`` is the
    realised speedup factor over plain greedy (≈1 on incompressible
    streams, up to ``k+1`` on perfectly predictable ones). Tokens are
    EXACTLY ``greedy_decode``'s. Jittable end-to-end; batch must be 1.
    """
    if prompt.shape[0] != 1:
        raise ValueError(
            f"speculative decode is a latency lever: batch must be 1, got "
            f"{prompt.shape[0]} (use greedy_decode for throughput batching)")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    t0 = prompt.shape[1]
    if max_len is None:
        max_len = t0 + n_new + k          # k slots of verification headroom
    if t0 + n_new + k > max_len:
        raise ValueError(
            f"prompt ({t0}) + n_new ({n_new}) + k ({k}) exceeds max_len "
            f"({max_len}) — speculation writes up to k draft rows past the "
            f"accepted position")

    cache = init_cache(cfg, 1, max_len, rules)
    logits, cache = forward_cached(
        params, prompt, cache, cfg, rules,
        prefill_impl=_select_prefill_impl(cfg, t0, prefill))
    first = jnp.argmax(logits[:, -1], axis=-1)           # [1]

    ctx0 = jnp.zeros((max_len,), prompt.dtype).at[:t0].set(prompt[0])
    ctx0 = ctx0.at[t0].set(first[0])

    state = {
        "cache": cache,
        "ctx": ctx0,                    # prompt + generated, flat [max_len]
        "n_out": jnp.int32(1),          # tokens generated so far
        "steps": jnp.int32(0),          # verification forwards run
    }

    def cond(s):
        return s["n_out"] < n_new

    def body(s):
        cur = t0 + s["n_out"]           # valid context length
        last = s["ctx"][cur - 1]
        draft = _ngram_draft(s["ctx"], cur, k, cfg.vocab)     # [k]
        block = jnp.concatenate([last[None], draft])[None]    # [1, k+1]
        # "cached": a mid-stream t>1 forward — the verification block
        # must attend over the cache buffer, never be mistaken for a
        # pos-0 prefill (which under an int8 cache reroutes to the
        # local full-precision k/v)
        logits, cache = forward_cached(params, block, s["cache"], cfg,
                                       rules, prefill_impl="cached")
        preds = jnp.argmax(logits[0], axis=-1)                # [k+1]
        # position j's prediction continues draft[j-1]; accept while the
        # draft agrees with the model's own argmax chain — the model
        # emits n_acc accepted drafts PLUS its own next token, capped so
        # we never exceed n_new
        new_toks, n_acc = accept_drafts(draft, preds)         # [k+1]
        emit = jnp.minimum(n_acc + 1, n_new - s["n_out"])
        keep = jnp.arange(k + 1) < emit
        upd = jax.lax.dynamic_slice_in_dim(s["ctx"], cur, k + 1)
        upd = jnp.where(keep, new_toks, upd)
        ctx = jax.lax.dynamic_update_slice_in_dim(s["ctx"], upd, cur, 0)
        # roll back: pos is the next input's position = count of stored
        # rows. The new un-forwarded last token sits at ctx[cur+emit-1],
        # so valid rows are [0, cur+emit-1); stale speculative rows
        # beyond are causally masked and later overwritten in place
        cache = dict(cache)
        cache["pos"] = cur + emit - 1
        return {"cache": cache, "ctx": ctx,
                "n_out": s["n_out"] + emit, "steps": s["steps"] + 1}

    final = jax.lax.while_loop(cond, body, state)
    toks = jax.lax.dynamic_slice_in_dim(final["ctx"], t0, n_new)
    return toks[None], final["steps"]


def make_speculative_decoder(cfg: BurnInConfig,
                             rules: ShardingRules | None = None,
                             n_new: int = 32, k: int = 4,
                             max_len: int | None = None,
                             telemetry=None):
    """Compiled speculative greedy decoder:
    ``decoder(params, prompt) → (tokens [1, n_new], steps)``.

    With telemetry enabled (``telemetry=`` injection or
    ``TPU_TELEMETRY_DIR``) each call emits a ``spec_decode`` span and
    counts accepted draft tokens: every verification step emits exactly
    one model token plus its accepted drafts, so ``n_new - steps`` IS
    the draft-token count speculation bought. The read of ``steps``
    syncs the call — instrumentation trades the async tail for the
    number; the disabled path returns the bare jitted callable.
    """
    fn = jax.jit(functools.partial(
        speculative_greedy_decode, n_new=n_new, cfg=cfg, rules=rules,
        k=k, max_len=max_len))
    from ..telemetry import get_registry

    reg = telemetry if telemetry is not None else get_registry()
    if not reg.enabled:
        return fn

    def instrumented(params, prompt):
        t0 = reg.clock()
        toks, steps = fn(params, prompt)
        steps_i = int(steps)            # d2h read: the honest span end
        t1 = reg.clock()
        reg.emit_span("spec_decode", t0, t1, n_new=n_new,
                      verify_steps=steps_i)
        reg.counter("spec_verify_steps").inc(steps_i)
        reg.counter("spec_accepted_draft_tokens").inc(
            max(0, n_new - steps_i))
        return toks, steps

    return instrumented
