"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Completes the parallelism portfolio the provisioned fabric must carry
(dp: gradient psum, tp: all-gather/reduce-scatter, sp: ring attention,
ep: MoE all-to-all, **pp: stage-to-stage activation ppermute**). The
reference has no workload at all (SURVEY §2.6); this is the TPU-idiomatic
pipeline design, not a port of a CUDA send/recv scheduler:

- **layers are data**: per-layer parameters stack into arrays with a
  leading layer dimension, sharded over ``pp`` — each stage holds
  ``n_layers / pp`` layers' weights and nothing else;
- **the schedule is a scan**: one ``lax.scan`` over ``M + pp - 1`` ticks;
  at every tick each stage runs its layers on its current microbatch and
  hands the activation to the next stage with a single ring
  ``ppermute``. No host control flow, no data-dependent shapes — the
  whole pipeline is one XLA program;
- **bubbles are masked, not branched**: warm-up/drain ticks compute on
  garbage and are excluded from the loss mask (XLA prefers uniform work
  over per-device control flow);
- **backward is free**: ``ppermute`` has a transpose rule, so
  ``jax.grad`` differentiates straight through the schedule — reverse
  ppermutes ARE the backward pipeline, no hand-written send/recv.

The block inside a stage is a plain dense transformer block (attention +
FFN). Pipeline composes with data parallelism (mesh ``("pp", "dp")``,
gradients pmean over dp) AND with tensor parallelism (mesh
``("pp", "dp", "tp")``): inside each stage, qkv/up are column-parallel
and wo/down row-parallel over ``tp``, with one explicit ``psum`` after
each row-parallel matmul — Megatron's schedule written manually, because
the whole pipeline body is already a Manual (shard_map) region where the
auto-sharding partitioner cannot reach. Sequence parallelism stays with
the non-pipelined paths.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.ring_attention import dense_reference_attention
from ..utils.layers import dense_init
from ..utils.layers import rmsnorm as _rmsnorm


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    d_ff: int = 128
    n_layers: int = 4
    seq_len: int = 32
    microbatch: int = 2        # examples per microbatch
    n_microbatches: int = 4
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_pipeline_params(rng, cfg: PipelineConfig):
    """Embed/head (replicated) + per-layer weights stacked on axis 0."""
    keys = jax.random.split(rng, 8)

    def dense(key, shape):
        return dense_init(key, shape, cfg.dtype)

    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    return {
        "embed": dense(keys[0], (cfg.vocab, D)),
        "out_norm": jnp.ones((D,), dtype=cfg.dtype),
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype=cfg.dtype),
            "wq": dense(keys[1], (L, D, D)),
            "wk": dense(keys[2], (L, D, D)),
            "wv": dense(keys[3], (L, D, D)),
            "wo": dense(keys[4], (L, D, D)),
            "mlp_norm": jnp.ones((L, D), dtype=cfg.dtype),
            "up": dense(keys[5], (L, D, F)),
            "down": dense(keys[6], (L, F, D)),
        },
    }



def _block(layer, x, cfg: PipelineConfig, tp: int = 1):
    """One dense transformer block; ``layer`` leaves have NO layer dim.

    Attention reuses ``dense_reference_attention`` (the same tested op the
    burn-in model's dense path calls) rather than re-deriving the math.

    With ``tp > 1`` (inside a shard_map carrying a ``tp`` axis) the layer
    leaves arrive ALREADY tp-sharded: wq/wk/wv/up hold their output
    columns' shard (heads split H/tp), wo/down hold their input rows'
    shard, and each row-parallel matmul's partial product is ``psum``'d
    over ``tp`` — the Megatron schedule, written out because the Manual
    region owns its collectives.
    """
    B, S, D = x.shape
    heads = cfg.n_heads // tp
    h = _rmsnorm(x, layer["attn_norm"])
    q = (h @ layer["wq"]).reshape(B, S, heads, cfg.head_dim)
    k = (h @ layer["wk"]).reshape(B, S, heads, cfg.head_dim)
    v = (h @ layer["wv"]).reshape(B, S, heads, cfg.head_dim)
    ctx = dense_reference_attention(q, k, v, causal=True)
    ctx = ctx.reshape(B, S, heads * cfg.head_dim)
    attn_out = ctx @ layer["wo"]
    if tp > 1:
        attn_out = jax.lax.psum(attn_out, "tp")
    x = x + attn_out
    h = _rmsnorm(x, layer["mlp_norm"])
    h = jax.nn.gelu((h @ layer["up"]).astype(jnp.float32)).astype(x.dtype)
    ffn_out = h @ layer["down"]
    if tp > 1:
        ffn_out = jax.lax.psum(ffn_out, "tp")
    return x + ffn_out


def _stage(stage_layers, x, cfg: PipelineConfig, tp: int = 1):
    """Apply this stage's stacked layers in order (scan over the local
    layer dim — still one compiled loop, not unrolled python)."""

    def body(carry, layer):
        return _block(layer, carry, cfg, tp), None

    out, _ = jax.lax.scan(body, x, stage_layers)
    return out


def _layer_specs(tp: int):
    """PartitionSpecs for the stacked layer dict: pp on the layer dim,
    tp on the Megatron dim of each weight (none when tp == 1)."""
    if tp == 1:
        p = P("pp")
        return {k: p for k in ("attn_norm", "wq", "wk", "wv", "wo",
                               "mlp_norm", "up", "down")}
    col, row = P("pp", None, "tp"), P("pp", "tp", None)
    return {
        "attn_norm": P("pp"), "mlp_norm": P("pp"),
        "wq": col, "wk": col, "wv": col, "up": col,
        "wo": row, "down": row,
    }


def pipeline_loss_fn(params, batch, cfg: PipelineConfig, mesh):
    """Pipelined forward + LM loss over a ``("pp", "dp")`` mesh.

    ``batch`` is ``(tokens, targets)`` of shape
    ``[n_microbatches · microbatch · dp, seq]``; inside the shard_map each
    dp shard sees ``[M, mb, S]`` microbatches. The scan runs
    ``M + pp - 1`` ticks; stage 0 feeds microbatch ``t``, stage ``i``
    works on microbatch ``t - i``, the last stage accumulates per-token
    NLL for valid ticks only. The scalar loss is psum'd over pp (only the
    last stage contributes) and pmean'd over dp.
    """
    # fail with named quantities, not a shard_map reshape error deep in jit
    if "pp" not in mesh.shape or "dp" not in mesh.shape:
        raise ValueError(
            f"pipeline needs a ('pp', 'dp'[, 'tp']) mesh; got axes "
            f"{tuple(mesh.axis_names)} (use dp=1 for no data parallelism)")
    pp = mesh.shape["pp"]
    dp = mesh.shape["dp"]
    tp = mesh.shape.get("tp", 1)
    M, mb, S = cfg.n_microbatches, cfg.microbatch, cfg.seq_len
    if cfg.n_layers % pp != 0:
        raise ValueError(
            f"n_layers = {cfg.n_layers} does not divide into pp = {pp} "
            f"stages")
    if tp > 1 and (cfg.n_heads % tp or cfg.d_ff % tp or cfg.d_model % tp):
        raise ValueError(
            f"tp = {tp} must divide n_heads ({cfg.n_heads}), d_ff "
            f"({cfg.d_ff}), and d_model ({cfg.d_model})")
    expected = M * mb * dp
    if batch[0].shape[0] != expected:
        raise ValueError(
            f"batch has {batch[0].shape[0]} rows; pipeline needs "
            f"n_microbatches·microbatch·dp = {M}·{mb}·{dp} = {expected}")

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(_layer_specs(tp), P(), P(), P(None, "dp")),
        out_specs=P(),
        check_vma=False,
    )
    def run(stage_layers, embed, out_norm, batch_shard):
        # stage_layers leaves: [L/pp, ...] (this stage's slice of the
        # layer stack); embed/out_norm replicated (explicit args, not
        # closure capture: committed Auto-sharded arrays captured inside
        # a Manual region break the backward pass's mesh context);
        # batch_shard: [2, B_local, S] (tokens, targets)
        i = jax.lax.axis_index("pp")
        tokens = batch_shard[0].reshape(M, mb, S)
        targets = batch_shard[1].reshape(M, mb, S)
        # embed/head live on every stage (replicated): stage 0 embeds,
        # the last stage projects — selected by masking, not branching
        x0 = embed[tokens]                              # [M, mb, S, D]

        def tick(carry, t):
            buf = carry                                  # [mb, S, D]
            feed = x0[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(i == 0, feed, buf)
            out = _stage(stage_layers, inp, cfg, tp)
            # last stage: LM head + NLL for its current microbatch
            h = _rmsnorm(out, out_norm)
            logits = (h @ embed.T).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            mb_idx = jnp.clip(t - (pp - 1), 0, M - 1)
            tgt = targets[mb_idx]
            nll = -jnp.take_along_axis(
                logp, tgt[..., None], axis=-1).squeeze(-1)
            valid = ((t - (pp - 1) >= 0) & (t - (pp - 1) < M) &
                     (i == pp - 1)).astype(jnp.float32)
            loss_t = valid * jnp.mean(nll)
            # hand the activation to the next stage (ring: the wrap-around
            # edge only ever carries drained garbage, masked above)
            nxt = jax.lax.ppermute(
                out, "pp", [(j, (j + 1) % pp) for j in range(pp)])
            return nxt, loss_t

        zero = jnp.zeros((mb, S, cfg.d_model), dtype=cfg.dtype)
        _, losses = jax.lax.scan(tick, zero, jnp.arange(M + pp - 1))
        # only the last stage accumulated loss: psum over pp recovers it
        # everywhere; pmean over dp averages data shards
        total = jax.lax.psum(jnp.sum(losses), "pp") / M
        return jax.lax.pmean(total, "dp")

    return run(params["layers"], params["embed"], params["out_norm"],
               jnp.stack(batch))


def stack_sharding(mesh, params):
    """NamedShardings: layer stacks over ``pp`` (+ Megatron ``tp`` dims
    when the mesh carries a tp axis), embed/head replicated."""
    tp = mesh.shape.get("tp", 1)
    specs = _layer_specs(tp)
    return {
        "embed": NamedSharding(mesh, P()),
        "out_norm": NamedSharding(mesh, P()),
        "layers": {k: NamedSharding(mesh, specs[k])
                   for k in params["layers"]},
    }


def make_pipeline_train_step(cfg: PipelineConfig, mesh, lr: float = 1e-3):
    """Jitted SGD step over the pipelined loss; grads flow through the
    reverse ppermutes (the backward pipeline autodiff derives)."""

    def step(params, batch):
        loss, grads = jax.value_and_grad(pipeline_loss_fn)(
            params, batch, cfg, mesh)
        params = jax.tree.map(
            lambda p, g: (p - lr * g.astype(p.dtype)), params, grads)
        return params, loss

    return jax.jit(step)


def reference_loss_fn(params, batch, cfg: PipelineConfig):
    """The same model WITHOUT the pipeline: every layer applied in order
    on one device — the equivalence oracle for the schedule."""
    tokens, targets = batch
    x = params["embed"][tokens]
    layers = params["layers"]
    n = layers["wq"].shape[0]
    for idx in range(n):
        layer = jax.tree.map(lambda a: a[idx], layers)
        x = _block(layer, x, cfg)
    h = _rmsnorm(x, params["out_norm"])
    logits = (h @ params["embed"].T).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)
