# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Expression evaluator with Terraform-style unknown-value propagation.

Anything not derivable at plan time (provider-computed attributes like a
cluster endpoint) evaluates to the :data:`COMPUTED` sentinel, which propagates
through every operation — exactly how a real plan renders
``(known after apply)``.
"""

from __future__ import annotations

from typing import Any

from . import ast as A
from .functions import FUNCTIONS, FunctionError


class EvalError(ValueError):
    pass


class _Computed:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<computed>"

    def __bool__(self):
        raise EvalError("cannot branch on a computed value at plan time")


COMPUTED = _Computed()


class _TryError:
    """Sentinel carried into try()/can() for failed lazy evaluations."""

    def __init__(self, error: Exception):
        self.error = error


def is_computed(v: Any) -> bool:
    if v is COMPUTED:
        return True
    if isinstance(v, list):
        return any(is_computed(x) for x in v)
    if isinstance(v, dict):
        return any(is_computed(x) for x in v.values())
    return False


class Scope:
    """Name resolution for one module evaluation."""

    def __init__(
        self,
        variables: dict[str, Any] | None = None,
        locals_: dict[str, Any] | None = None,
        resources: dict[str, dict[str, Any]] | None = None,
        data: dict[str, dict[str, Any]] | None = None,
        modules: dict[str, Any] | None = None,
        each: Any = None,
        count_index: int | None = None,
        path_module: str = ".",
        workspace: str = "default",
    ):
        self.variables = variables or {}
        self.locals = locals_ or {}
        self.resources = resources or {}
        self.data = data or {}
        self.modules = modules or {}
        self.each = each
        self.count_index = count_index
        self.path_module = path_module
        self.workspace = workspace
        self.bindings: dict[str, Any] = {}  # for-expression vars

    def child_bindings(self, **kw: Any) -> "Scope":
        s = Scope(
            self.variables, self.locals, self.resources, self.data,
            self.modules, self.each, self.count_index, self.path_module,
            self.workspace,
        )
        s.bindings = {**self.bindings, **kw}
        return s


def evaluate(expr: A.Expr, scope: Scope) -> Any:
    return _Evaluator(scope).eval(expr)


class _Evaluator:
    def __init__(self, scope: Scope):
        self.scope = scope

    def eval(self, e: A.Expr) -> Any:
        m = getattr(self, f"_eval_{type(e).__name__}", None)
        if m is None:
            raise EvalError(f"cannot evaluate node {type(e).__name__}")
        return m(e)

    # ----------------------------------------------------------- literals
    def _eval_Literal(self, e: A.Literal):
        return e.value

    def _eval_Template(self, e: A.Template):
        parts = []
        for p in e.parts:
            if isinstance(p, str):
                parts.append(p)
            else:
                v = self.eval(p)
                if v is COMPUTED:
                    return COMPUTED
                parts.append(_stringify(v))
        return "".join(parts)

    def _eval_TupleExpr(self, e: A.TupleExpr):
        return [self.eval(x) for x in e.items]

    def _eval_ObjectExpr(self, e: A.ObjectExpr):
        out = {}
        for item in e.items:
            k = self.eval(item.key)
            if k is COMPUTED:
                raise EvalError("computed map key at plan time")
            out[_stringify(k)] = self.eval(item.value)
        return out

    # ---------------------------------------------------------- operators
    def _eval_Unary(self, e: A.Unary):
        v = self.eval(e.operand)
        if v is COMPUTED:
            return COMPUTED
        if e.op == "!":
            return not v
        if e.op == "-":
            return -v
        raise EvalError(f"unary {e.op}")

    def _eval_Binary(self, e: A.Binary):
        l = self.eval(e.left)
        r = self.eval(e.right)
        if l is COMPUTED or r is COMPUTED:
            return COMPUTED
        ops = {
            "==": lambda: l == r, "!=": lambda: l != r,
            "<": lambda: l < r, ">": lambda: l > r,
            "<=": lambda: l <= r, ">=": lambda: l >= r,
            "+": lambda: l + r, "-": lambda: l - r,
            "*": lambda: l * r, "/": lambda: l / r, "%": lambda: l % r,
            "&&": lambda: bool(l) and bool(r), "||": lambda: bool(l) or bool(r),
        }
        if e.op not in ops:
            raise EvalError(f"binary {e.op}")
        return ops[e.op]()

    def _eval_Conditional(self, e: A.Conditional):
        c = self.eval(e.cond)
        if c is COMPUTED:
            return COMPUTED
        return self.eval(e.if_true) if c else self.eval(e.if_false)

    # ---------------------------------------------------------- traversals
    def _eval_Traversal(self, e: A.Traversal):
        if hasattr(e, "root_expr"):
            value = self.eval(e.root_expr)  # type: ignore[attr-defined]
            ops = e.ops
        else:
            value, ops = self._resolve_root(e)
        return self._apply_ops(value, ops, e)

    def _resolve_root(self, e: A.Traversal):
        s = self.scope
        root = e.root
        if root in s.bindings:
            return s.bindings[root], e.ops
        if root == "var":
            return self._attr_step(s.variables, e.ops, e, "variable")
        if root == "local":
            return self._attr_step(s.locals, e.ops, e, "local")
        if root == "each":
            if s.each is None:
                raise EvalError("each.* used outside for_each context")
            return s.each, e.ops
        if root == "count":
            if s.count_index is None:
                raise EvalError("count.index used outside count context")
            return {"index": s.count_index}, e.ops
        if root == "path":
            return {"module": s.path_module, "root": s.path_module, "cwd": "."}, e.ops
        if root == "terraform":
            return {"workspace": s.workspace}, e.ops
        if root == "data":
            if not e.ops or e.ops[0][0] != "attr":
                raise EvalError("data reference needs a type")
            dtype = e.ops[0][1]
            if dtype not in s.data:
                raise EvalError(f"unknown data source type {dtype!r}")
            return self._attr_step(s.data[dtype], e.ops[1:], e, f"data.{dtype}")
        if root == "module":
            return self._attr_step(s.modules, e.ops, e, "module")
        if root in s.resources:
            return self._attr_step(s.resources[root], e.ops, e, f"resource {root}")
        raise EvalError(f"unknown reference {e.path_str()!r}")

    def _attr_step(self, table: dict, ops: list, e: A.Traversal, what: str):
        if not ops or ops[0][0] != "attr":
            return table, ops
        name = ops[0][1]
        if name not in table:
            raise EvalError(f"{what} {name!r} not declared (in {e.path_str()})")
        return table[name], ops[1:]

    def _apply_ops(self, value: Any, ops: list, e: A.Traversal):
        for i, op in enumerate(ops):
            if value is COMPUTED:
                return COMPUTED
            if op[0] == "attr":
                if isinstance(value, dict):
                    try:
                        value = value[op[1]]  # ResourceAttrs yields COMPUTED
                    except KeyError:
                        raise EvalError(
                            f"attribute {op[1]!r} not present (in {e.path_str()})"
                        )
                else:
                    raise EvalError(f"cannot access .{op[1]} on {type(value).__name__}")
            elif op[0] == "index":
                idx = self.eval(op[1])
                if idx is COMPUTED:
                    return COMPUTED
                try:
                    value = value[int(idx) if isinstance(value, list) else idx]
                except (KeyError, IndexError, TypeError) as ex:
                    raise EvalError(f"index {idx!r} failed on {e.path_str()}: {ex}")
            elif op[0] == "splat":
                rest = ops[i + 1:]
                if value is None:
                    return []
                if not isinstance(value, list):
                    value = [value]
                return [self._apply_ops(v, rest, e) for v in value]
        return value

    # ---------------------------------------------------------- functions
    def _eval_Call(self, e: A.Call):
        if e.name in ("try", "can"):
            return self._lazy_call(e)
        args = []
        for i, a in enumerate(e.args):
            v = self.eval(a)
            if e.expand_last and i == len(e.args) - 1:
                if v is COMPUTED:
                    return COMPUTED
                args.extend(v)
            else:
                args.append(v)
        if e.name not in FUNCTIONS:
            raise EvalError(f"function {e.name!r} not in tfsim subset")
        if any(v is COMPUTED for v in args):
            return COMPUTED
        try:
            return FUNCTIONS[e.name](*args)
        except FunctionError:
            raise
        except Exception as ex:
            raise EvalError(f"{e.name}(): {ex}")

    def _lazy_call(self, e: A.Call):
        results = []
        for a in e.args:
            try:
                results.append(self.eval(a))
            except (EvalError, FunctionError) as ex:
                results.append(_TryError(ex))
        if e.name == "can":
            return not isinstance(results[0], _TryError)
        for r in results:
            if not isinstance(r, _TryError):
                return r
        raise EvalError("try(): all expressions failed")

    # ------------------------------------------------------- comprehensions
    def _eval_ForExpr(self, e: A.ForExpr):
        coll = self.eval(e.collection)
        if coll is COMPUTED:
            return COMPUTED
        if isinstance(coll, dict):
            pairs = [(k, coll[k]) for k in coll]
        else:
            pairs = list(enumerate(coll))
        if e.key_expr is None:
            out_list = []
            for k, v in pairs:
                sub = self._bind(e, k, v)
                if e.cond is not None:
                    c = sub.eval(e.cond)
                    if c is COMPUTED:
                        return COMPUTED
                    if not c:
                        continue
                out_list.append(sub.eval(e.value_expr))
            return out_list
        out: dict = {}
        for k, v in pairs:
            sub = self._bind(e, k, v)
            if e.cond is not None:
                c = sub.eval(e.cond)
                if c is COMPUTED:
                    return COMPUTED
                if not c:
                    continue
            key = _stringify(sub.eval(e.key_expr))
            val = sub.eval(e.value_expr)
            if e.grouping:
                out.setdefault(key, []).append(val)
            else:
                out[key] = val
        return out

    def _bind(self, e: A.ForExpr, k, v) -> "_Evaluator":
        kw = {e.value_var: v}
        if e.key_var:
            kw[e.key_var] = k
        return _Evaluator(self.scope.child_bindings(**kw))


def _stringify(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)
