# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""AST node types for the tfsim HCL2 subset."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union


class Node:
    line: int = 0


@dataclasses.dataclass
class Literal(Node):
    value: Any            # str | int | float | bool | None
    line: int = 0


@dataclasses.dataclass
class Template(Node):
    """Interpolated string: parts are str literals or embedded expressions."""

    parts: list[Union[str, "Expr"]]
    line: int = 0


@dataclasses.dataclass
class TupleExpr(Node):
    items: list["Expr"]
    line: int = 0


@dataclasses.dataclass
class ObjectItem(Node):
    key: "Expr"           # Literal(str) for bare idents, else arbitrary expr
    value: "Expr"
    line: int = 0


@dataclasses.dataclass
class ObjectExpr(Node):
    items: list[ObjectItem]
    line: int = 0


@dataclasses.dataclass
class Traversal(Node):
    """`var.x`, `google_container_cluster.c[0].name`, `a.b[*].id` ..."""

    root: str
    ops: list[tuple]      # ("attr", name) | ("index", Expr) | ("splat",)
    line: int = 0

    def path_str(self) -> str:
        out = self.root
        for op in self.ops:
            if op[0] == "attr":
                out += f".{op[1]}"
            elif op[0] == "index":
                out += "[…]"
            else:
                out += "[*]"
        return out


@dataclasses.dataclass
class Call(Node):
    name: str
    args: list["Expr"]
    expand_last: bool = False   # f(a, b...)
    line: int = 0


@dataclasses.dataclass
class Unary(Node):
    op: str
    operand: "Expr"
    line: int = 0


@dataclasses.dataclass
class Binary(Node):
    op: str
    left: "Expr"
    right: "Expr"
    line: int = 0


@dataclasses.dataclass
class Conditional(Node):
    cond: "Expr"
    if_true: "Expr"
    if_false: "Expr"
    line: int = 0


@dataclasses.dataclass
class ForExpr(Node):
    """`[for v in coll : expr if cond]` / `{for k, v in coll : k => v}`"""

    key_var: Optional[str]      # None for single-var form
    value_var: str
    collection: "Expr"
    key_expr: Optional["Expr"]  # set → object form
    value_expr: "Expr"
    cond: Optional["Expr"]
    grouping: bool = False      # `=>` followed by `...`
    line: int = 0


Expr = Union[
    Literal, Template, TupleExpr, ObjectExpr, Traversal, Call, Unary, Binary,
    Conditional, ForExpr,
]


@dataclasses.dataclass
class Attribute(Node):
    name: str
    expr: Expr
    line: int = 0


@dataclasses.dataclass
class Block(Node):
    type: str
    labels: list[str]
    body: "Body"
    line: int = 0


@dataclasses.dataclass
class Body(Node):
    attributes: list[Attribute]
    blocks: list[Block]
    line: int = 0

    def attr(self, name: str) -> Optional[Attribute]:
        for a in self.attributes:
            if a.name == name:
                return a
        return None

    def blocks_of(self, type_: str) -> list[Block]:
        return [b for b in self.blocks if b.type == type_]


def walk(node) -> "list[Node]":
    """Flatten an AST (or Body) into a node list, depth-first."""
    out: list[Node] = []

    def rec(x):
        if isinstance(x, Node):
            out.append(x)
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            for f in dataclasses.fields(x):
                rec(getattr(x, f.name))
        elif isinstance(x, (list, tuple)):
            for item in x:
                rec(item)
    rec(node)
    return out


def scoped_traversals(node, bound: frozenset = frozenset()):
    """Yield ``(Traversal, bound_names)`` pairs with correct lexical scoping.

    The single source of truth for scope-aware AST walking, shared by the
    validator (reference checking) and the planner (dependency extraction):
    for-expression variables and ``dynamic`` block iterators are tracked as
    bound names; ``lifecycle`` attributes are skipped (their
    ``ignore_changes`` entries are attribute names, not references) but
    ``precondition``/``postcondition`` bodies are real expressions and are
    walked.
    """
    if isinstance(node, ForExpr):
        names = {node.value_var} | ({node.key_var} if node.key_var else set())
        yield from scoped_traversals(node.collection, bound)
        inner = bound | names
        for sub in (node.key_expr, node.value_expr, node.cond):
            if sub is not None:
                yield from scoped_traversals(sub, inner)
        return
    if isinstance(node, Block):
        if node.type == "lifecycle":
            for b in node.body.blocks:
                if b.type in ("precondition", "postcondition"):
                    yield from scoped_traversals(b.body, bound)
            return
        if node.type == "dynamic" and node.labels:
            iterator = node.labels[0]
            it_attr = node.body.attr("iterator")
            if it_attr is not None and isinstance(it_attr.expr, Traversal):
                iterator = it_attr.expr.root
            fe = node.body.attr("for_each")
            if fe is not None:
                yield from scoped_traversals(fe.expr, bound)
            for content in node.body.blocks_of("content"):
                yield from scoped_traversals(content, bound | {iterator})
            return
    if isinstance(node, Traversal):
        yield node, bound
        if hasattr(node, "root_expr"):
            yield from scoped_traversals(node.root_expr, bound)
        for op in node.ops:
            if op[0] == "index":
                yield from scoped_traversals(op[1], bound)
        return
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            yield from scoped_traversals(getattr(node, f.name), bound)
    elif isinstance(node, (list, tuple)):
        for item in node:
            yield from scoped_traversals(item, bound)
