# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""``tfsim console`` — evaluate HCL expressions against a planned module.

Terraform's ``console`` is the operator's probe into a configuration: it
resolves ``var.*`` / ``local.*`` / resource attributes / functions the same
way plan does, which the reference's README-driven workflow leans on for
debugging variable wiring. tfsim ships the same verb offline: the module is
planned once (so resource attributes carry their plan-time values, computed
ones render as ``<computed>``), then each expression is parsed and evaluated
in that scope.

Values print as JSON (tfsim's canonical rendering — ``plan -json`` uses the
same), not terraform's HCL-ish syntax; sensitive outputs are NOT masked here,
matching ``terraform console``'s behaviour of resolving raw values.
"""

from __future__ import annotations

from typing import Any

from .eval import EvalError, Scope, evaluate
from .module import Module
from .parser import HclParseError, parse_hcl
from .plan import LazyLocals, Plan, plan_eval_scope


class ConsoleError(ValueError):
    pass


def build_scope(module: Module, plan: Plan,
                workspace: str = "default") -> Scope:
    """Evaluation scope with vars, locals, planned resources, and outputs."""
    scope = plan_eval_scope(plan, plan.variables)
    scope.locals = LazyLocals(module.locals, scope)
    scope.path_module = module.path
    scope.workspace = workspace
    return scope


def parse_expression(text: str):
    """Parse one HCL expression (console input line) into an AST."""
    try:
        body = parse_hcl(f"__console = {text.strip()}", filename="<console>")
    except HclParseError as ex:
        raise ConsoleError(str(ex))
    if len(body.attributes) != 1 or body.blocks:
        raise ConsoleError(f"not a single expression: {text.strip()!r}")
    return body.attributes[0].expr


def eval_expression(text: str, scope: Scope) -> Any:
    try:
        return evaluate(parse_expression(text), scope)
    except EvalError as ex:
        raise ConsoleError(str(ex))
