# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Expert-parallel MoE: routing correctness, sharded equivalence, training.

The ep axis is the fourth first-class parallelism axis the provisioned
fabric must carry (dp: psum, tp: all-gather/reduce-scatter, sp: ring,
ep: all-to-all dispatch). Everything runs on the virtual 8-device CPU
mesh; sharded runs must match unsharded bit-for-bit-ish (fp tolerance).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    expert_capacity,
    forward_and_aux,
    init_moe_params,
    init_params,
    make_train_step,
    moe_layer,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.parallel import (
    build_mesh,
    make_rules,
    plan_mesh,
)

CFG = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                   seq_len=16, batch=8, dtype=jnp.float32, n_experts=4)


def test_expert_capacity_tiles():
    assert expert_capacity(128, 4, 1.25) == 40
    assert expert_capacity(8, 8, 1.0) == 8      # floor at a sublane tile
    assert expert_capacity(1000, 4, 1.25) % 8 == 0


def test_single_expert_equals_dense_mlp():
    """E=1 with ample capacity routes every token through the one expert
    with gate 1.0 — the MoE layer must equal the plain FFN exactly."""
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=4,
                       dtype=jnp.float32, n_experts=1, capacity_factor=2.0)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 32), jnp.float32)
    out, aux = moe_layer(x, params, cfg)
    dense = jax.nn.gelu(
        (x.reshape(-1, 32) @ params["experts_up"][0]).astype(jnp.float32)
    ).astype(jnp.float32) @ params["experts_down"][0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense.reshape(4, 16, 32)),
        rtol=1e-5, atol=1e-5)
    assert float(aux) == pytest.approx(1.0)  # E·1·1: all mass on one expert


def test_moe_routes_to_multiple_experts():
    params = init_moe_params(jax.random.PRNGKey(0), CFG)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 32), jnp.float32)
    logits = x.reshape(-1, 32) @ params["router"]
    experts_used = len(set(np.asarray(jnp.argmax(logits, -1)).tolist()))
    assert experts_used >= 2          # random init routes non-trivially
    out, aux = moe_layer(x, params, CFG)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) >= 1.0          # Switch aux is minimised at 1.0


def test_tiny_capacity_drops_tokens_but_stays_finite():
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=64, batch=8,
                       dtype=jnp.float32, n_experts=4,
                       capacity_factor=0.05)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 64, 32), jnp.float32)
    out, _ = moe_layer(x, params, cfg)
    # dropped tokens contribute zeros (residual path carries them)
    dropped = np.asarray(jnp.all(out.reshape(-1, 32) == 0.0, axis=-1))
    assert dropped.any()
    assert np.isfinite(np.asarray(out)).all()


def test_ep_mesh_plan_and_rules(jax8):
    plan = plan_mesh(8, ep=2, tp=2)
    assert plan.axis_names == ("dp", "ep", "sp", "tp")
    assert plan.shape == (2, 2, 1, 2)
    rules = make_rules(build_mesh(plan))
    assert rules.data == ("dp", "ep")
    # dense meshes stay 3-axis
    assert plan_mesh(8).axis_names == ("dp", "sp", "tp")


def test_sharded_moe_matches_unsharded(jax8):
    """The whole MoE forward on a dp×ep×tp mesh equals single-device."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), CFG)
    ref, ref_aux = forward_and_aux(params, tokens, CFG)

    rules = make_rules(build_mesh(plan_mesh(8, ep=2, tp=2)))
    sharded_params = init_params(jax.random.PRNGKey(0), CFG, rules)
    got, got_aux = forward_and_aux(sharded_params, tokens, CFG, rules)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert float(got_aux) == pytest.approx(float(ref_aux), rel=1e-4)


def test_moe_train_step_decreases_loss_on_ep_mesh(jax8):
    rules = make_rules(build_mesh(plan_mesh(8, ep=2, tp=2)))
    params = init_params(jax.random.PRNGKey(0), CFG, rules)
    step = make_train_step(CFG, rules)
    batch = synthetic_batch(jax.random.PRNGKey(1), CFG, rules)
    losses = []
    for _ in range(5):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(losses))


def test_moe_checkpoint_roundtrip(tmp_path, jax8):
    """Expert-sharded params survive the orbax save/restore cycle with
    shardings intact — spot-slice resume covers MoE workloads too."""
    from nvidia_terraform_modules_tpu.models import (
        restore_checkpoint,
        save_checkpoint,
    )

    rules = make_rules(build_mesh(plan_mesh(8, ep=2, tp=2)))
    params = init_params(jax.random.PRNGKey(0), CFG, rules)
    save_checkpoint(str(tmp_path), 1, params)
    restored, _, _ = restore_checkpoint(str(tmp_path), CFG, rules)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding


def test_plan_mesh_rejects_mismatched_axis_names():
    with pytest.raises(ValueError, match="adds an axis"):
        plan_mesh(8, ep=2, axis_names=("dp", "sp", "tp"))


def test_top2_matches_handrolled_reference():
    """GShard top-2 vs a capacity-free reference: with generous capacity
    (nothing drops), the layer output must equal the direct mixture
    Σ_r gate_r · FFN_{expert_r}(token) — this fails if rank-1 dispatch
    is ever lost."""
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models.moe import moe_layer

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=8, batch=2, n_experts=4, router_top_k=2,
                       capacity_factor=4.0, dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32), jnp.float32)
    out, aux = moe_layer(x, params, cfg)
    assert float(aux) > 0

    tokens = x.reshape(16, 32)
    logits = tokens @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, 2)
    gates = top_p / top_p.sum(-1, keepdims=True)

    def expert_ffn(e, tok):
        h = jax.nn.gelu(tok @ params["experts_up"][e])
        return h @ params["experts_down"][e]

    ref = jnp.stack([
        sum(gates[t, r] * expert_ffn(int(top_e[t, r]), tokens[t])
            for r in range(2))
        for t in range(16)
    ]).reshape(2, 8, 32)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_top1_path_is_unchanged_by_topk_generalisation():
    """k=1 must reproduce the original Switch layer exactly."""
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models.moe import moe_layer

    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                seq_len=8, batch=2, n_experts=4, dtype=jnp.float32)
    cfg = BurnInConfig(**base)                      # router_top_k defaults 1
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32), jnp.float32)
    out, aux = moe_layer(x, params, cfg)
    # hand-rolled original top-1 reference
    tokens = x.reshape(16, 32)
    logits = tokens @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    from nvidia_terraform_modules_tpu.models.moe import expert_capacity
    C = expert_capacity(16, 4, cfg.capacity_factor)
    oh = jax.nn.one_hot(expert, 4, dtype=jnp.int32)
    pos = jnp.cumsum(oh, axis=0) * oh - oh
    within = ((pos < C) & (oh == 1)).astype(jnp.float32)
    dispatch = jax.nn.one_hot(pos, C) * within[..., None]
    combine = dispatch * gate[:, None, None]
    xin = jnp.einsum("tec,td->ecd", dispatch, tokens)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin, params["experts_up"]))
    xout = jnp.einsum("ecf,efd->ecd", h, params["experts_down"])
    ref = jnp.einsum("tec,ecd->td", combine, xout).reshape(2, 8, 32)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_top2_trains_on_ep_mesh(jax8):
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, n_experts=4, router_top_k=2)
    mesh = build_mesh(plan_mesh(8, ep=2, tp=2))
    rules = make_rules(mesh)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(6):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_router_top_k_validated():
    import pytest

    with pytest.raises(ValueError, match="router_top_k"):
        BurnInConfig(n_experts=4, router_top_k=5)
    with pytest.raises(ValueError, match="router_top_k"):
        BurnInConfig(router_top_k=0)
    with pytest.raises(ValueError, match="needs n_experts"):
        BurnInConfig(router_top_k=2)   # dense model, no router
