# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Mesh construction, sharding rules, collective probes, multi-host bootstrap.

The reference provisions the *fabric* (node-to-node security-group rules,
``/root/reference/eks/main.tf:28-49``) and delegates collectives to NCCL inside
user pods. Our TPU-native equivalent: the Terraform layer provisions slice
topology (ICI) and this package exercises it with XLA collectives over a
``jax.sharding.Mesh``.
"""

from .mesh import MeshPlan, build_mesh, plan_mesh  # noqa: F401
from .multislice import (  # noqa: F401
    build_multislice_mesh,
    dcn_slice_count,
    group_devices_by_slice,
    plan_elastic_multislice,
    plan_multislice,
)
from .sharding import ShardingRules, make_rules  # noqa: F401
from .collectives import (  # noqa: F401
    all_gather_probe,
    hierarchical_psum,
    hierarchical_psum_probe,
    psum_probe,
    reduce_scatter_probe,
    ring_permute_probe,
)
from .multihost import (  # noqa: F401
    DistributedInitError,
    job_env_from_environ,
    maybe_initialize_distributed,
)
from .pipeline import (  # noqa: F401
    PipelineConfig,
    init_pipeline_params,
    make_pipeline_train_step,
    pipeline_loss_fn,
    pipeline_value_and_grad_1f1b,
    stack_sharding,
)
