# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Input surface of the GPU-parity GKE module.
#
# Capability parity with the reference's 24 variables
# (/root/reference/gke/variables.tf:7-145): project/region/zone selection,
# bring-your-own network, cluster channel/version, CPU + GPU pool shaping,
# spot capacity, and GPU Operator pinning — expressed with modern typed
# objects instead of parallel scalar variables.

variable "project_id" {
  description = "GCP project to deploy into."
  type        = string
}

variable "cluster_name" {
  description = "Name of the GKE cluster (also used as a prefix for network resources)."
  type        = string
  default     = "accel-cluster"
}

variable "region" {
  description = "Region for the cluster and its network."
  type        = string
  default     = "us-central1"
}

variable "node_zones" {
  description = "Zones for node placement. Exactly one zone produces a zonal cluster; multiple zones produce a regional cluster spanning them."
  type        = list(string)
  default     = ["us-central1-a"]

  validation {
    condition     = length(var.node_zones) > 0
    error_message = "At least one node zone is required."
  }
}

variable "release_channel" {
  description = "GKE release channel (RAPID, REGULAR, STABLE, or UNSPECIFIED to pin min_master_version)."
  type        = string
  default     = "REGULAR"
}

variable "min_master_version" {
  description = "Minimum master version when release_channel is UNSPECIFIED; ignored otherwise."
  type        = string
  default     = null
}

variable "deletion_protection" {
  description = "Protect the cluster from accidental terraform destroy."
  type        = bool
  default     = false
}

# ---------------------------------------------------------------- network

variable "network" {
  description = <<-EOT
    Network configuration. With create = true a dedicated VPC and subnet are
    provisioned; with create = false, existing_network / existing_subnetwork
    must name the network to attach to (bring-your-own, the reference's
    vpc_enabled / existing_vpc_details toggle).
  EOT
  type = object({
    create              = optional(bool, true)
    subnet_cidr         = optional(string, "10.150.0.0/20")
    existing_network    = optional(string)
    existing_subnetwork = optional(string)
  })
  default = {}
}

# ---------------------------------------------------------------- CPU pool

variable "cpu_pool" {
  description = "Shape of the general-purpose (CPU) node pool."
  type = object({
    machine_type  = optional(string, "n2-standard-8")
    min_nodes     = optional(number, 1)
    max_nodes     = optional(number, 5)
    initial_nodes = optional(number, 1)
    disk_size_gb  = optional(number, 100)
    disk_type     = optional(string, "pd-balanced")
    image_type    = optional(string, "COS_CONTAINERD")
    spot          = optional(bool, false)
    labels        = optional(map(string), {})
  })
  default = {}
}

# ---------------------------------------------------------------- GPU pool

variable "gpu_pool" {
  description = <<-EOT
    Shape of the accelerator node pool. gpu_type/gpu_count mirror the
    reference's guest_accelerator knobs (e.g. nvidia-tesla-v100 x1); set
    enabled = false for a CPU-only cluster (baseline config 1).
  EOT
  type = object({
    enabled       = optional(bool, true)
    machine_type  = optional(string, "n1-standard-8")
    gpu_type      = optional(string, "nvidia-tesla-v100")
    gpu_count     = optional(number, 1)
    min_nodes     = optional(number, 1)
    max_nodes     = optional(number, 5)
    initial_nodes = optional(number, 2)
    disk_size_gb  = optional(number, 512)
    disk_type     = optional(string, "pd-ssd")
    image_type    = optional(string, "UBUNTU_CONTAINERD")
    spot          = optional(bool, false)
    labels        = optional(map(string), {})
  })
  default = {}
}

# ------------------------------------------------------------ GPU Operator

variable "gpu_operator" {
  description = <<-EOT
    NVIDIA GPU Operator install knobs: Helm chart version, driver branch, and
    target namespace (reference: gpu_operator_version /
    gpu_operator_driver_version / gpu_operator_namespace).
  EOT
  type = object({
    enabled        = optional(bool, true)
    version        = optional(string, "v25.3.0")
    driver_version = optional(string, "570.124.06")
    namespace      = optional(string, "gpu-operator")
  })
  default = {}
}

# ----------------------------------------------------- control-plane security

variable "database_encryption" {
  description = <<-EOT
    Application-layer encryption of Kubernetes secrets in etcd with a
    Cloud KMS key (CMEK) — the GKE analogue of the reference EKS module's
    KMS secret encryption (eks/main.tf:64-72). With enabled = true and no
    kms_key_name, the module creates a keyring + key (rotation like the
    reference's enable_key_rotation) and grants the GKE service agent
    use of it; bring your own key via kms_key_name.
  EOT
  type = object({
    enabled             = optional(bool, false)
    kms_key_name        = optional(string)
    key_rotation_period = optional(string, "7776000s") # 90 days
  })
  default = {}

  validation {
    condition     = var.database_encryption.enabled || var.database_encryption.kms_key_name == null
    error_message = "database_encryption.kms_key_name without enabled = true would silently not encrypt — enable it or drop the key."
  }
}

variable "authenticator_security_group" {
  description = <<-EOT
    Google Groups for RBAC: the gke-security-groups@<your-domain> umbrella
    group wired into the control plane so RoleBindings can name Google
    groups — the GKE analogue of AKS admin-group RBAC
    (aks/main.tf:36-40). null leaves group authentication off.
  EOT
  type    = string
  default = null

  validation {
    condition     = (var.authenticator_security_group == null || startswith(coalesce(var.authenticator_security_group, "-"), "gke-security-groups@"))
    error_message = "GKE requires the umbrella group to be named gke-security-groups@<your-domain>."
  }
}
