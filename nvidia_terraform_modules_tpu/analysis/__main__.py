# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""graftlint CLI — ``python -m nvidia_terraform_modules_tpu.analysis``.

Usage:
    python -m nvidia_terraform_modules_tpu.analysis [DIR]
        [-json | -sarif] [-severity RULE=LEVEL ...] [-rules]

DIR defaults to the installed runtime package itself, so a bare
invocation is the CI gate: exit 2 on error findings, 1 on warnings,
0 clean (info never fails a build). Same flag surface, output formats
and exit-code contract as ``tfsim lint`` — both CLIs are thin bindings
of the shared engine in :mod:`.core`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import Finding, exit_code, findings_json, sarif_report
from .graftlint import list_rules, run_graftlint

_PY_SUFFIXES = (".py",)

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m nvidia_terraform_modules_tpu.analysis",
        description="graftlint: runtime-convention static analysis for "
                    "the JAX serving stack")
    p.add_argument("dir", nargs="?", default=_PACKAGE_DIR)
    p.add_argument("-json", action="store_true")
    p.add_argument("-sarif", action="store_true")
    p.add_argument("-severity", action="append", dest="severity",
                   metavar="RULE=LEVEL")
    p.add_argument("-rules", action="store_true",
                   help="list the rule catalog and exit")
    args = p.parse_args(argv)

    if args.rules:
        for r in list_rules():
            print(f"{r.id:32} {r.severity:8} {r.family:12} {r.summary}")
        return 0

    try:
        overrides: dict[str, str] = {}
        for kv in args.severity or []:
            if "=" not in kv:
                raise ValueError(
                    f"-severity expects RULE=LEVEL, got {kv!r}")
            rid, _, level = kv.partition("=")
            overrides[rid.strip()] = level.strip()
        findings = run_graftlint(args.dir, overrides=overrides)
    except (ValueError, OSError) as ex:
        # a bad flag or an unreadable tree is a diagnostic in every
        # output format, never a traceback — same contract as tfsim lint
        findings = [Finding("error", "", str(ex), rule="graft-load")]
    counts = {s: sum(1 for f in findings if f.severity == s)
              for s in ("error", "warning", "info")}
    rc = exit_code(findings)
    if args.sarif:
        print(json.dumps(
            sarif_report(findings, list_rules(), "graftlint",
                         _PY_SUFFIXES),
            indent=2, sort_keys=True))
        return rc
    if args.json:
        print(json.dumps(findings_json(findings, _PY_SUFFIXES),
                         indent=2, sort_keys=True))
        return rc
    for f in findings:
        where = f"{f.where}: " if f.where else ""
        print(f"{where}{f.severity}: {f.message} [{f.rule}]")
    print(f"{'Success! ' if rc == 0 else ''}{len(findings)} finding(s): "
          f"{counts['error']} error(s), {counts['warning']} warning(s), "
          f"{counts['info']} info.")
    return rc


if __name__ == "__main__":
    sys.exit(main())
