# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# GKE control plane (L2) and version discovery.
#
# Capability parity with google_container_cluster.holoscan
# (/root/reference/gke/main.tf:31-56): zonal-vs-regional placement from the
# zone list, default node pool removed in favour of explicitly managed pools,
# Workload Identity enabled, release-channel driven versioning plus a
# latest-version data probe surfaced through outputs.

data "google_container_engine_versions" "channel" {
  provider = google-beta

  project  = var.project_id
  location = local.cluster_location
}

locals {
  # one zone → zonal cluster pinned to it; several → regional cluster
  zonal            = length(var.node_zones) == 1
  cluster_location = local.zonal ? one(var.node_zones) : var.region
  pool_zones       = local.zonal ? null : var.node_zones
}

resource "google_container_cluster" "this" {
  name     = var.cluster_name
  project  = var.project_id
  location = local.cluster_location

  network    = local.network_name
  subnetwork = local.subnetwork_name

  # pools are managed as first-class resources below; the implicit default
  # pool is created only to be removed
  remove_default_node_pool = true
  initial_node_count       = 1

  deletion_protection = var.deletion_protection

  dynamic "release_channel" {
    for_each = var.release_channel == "UNSPECIFIED" ? [] : [var.release_channel]
    content {
      channel = release_channel.value
    }
  }

  min_master_version = var.release_channel == "UNSPECIFIED" ? var.min_master_version : null

  workload_identity_config {
    workload_pool = "${var.project_id}.svc.id.goog"
  }

  # CMEK secrets-at-rest (reference EKS parity — see security.tf); the
  # provider default is Google-managed encryption, so the block only
  # renders when the operator opted in
  dynamic "database_encryption" {
    for_each = var.database_encryption.enabled ? [1] : []
    content {
      state    = "ENCRYPTED"
      key_name = local.secrets_kms_key
    }
  }

  # Google Groups for RBAC (reference AKS admin-groups parity)
  dynamic "authenticator_groups_config" {
    for_each = var.authenticator_security_group == null ? [] : [var.authenticator_security_group]
    content {
      security_group = authenticator_groups_config.value
    }
  }

  timeouts {
    create = "45m"
    update = "30m"
    delete = "45m"
  }

  # CMEK needs the service-agent grant BEFORE control-plane creation —
  # the key reference alone orders only against the key, and a cluster
  # racing ahead of the IAM member fails with CloudKMS access denied
  depends_on = [google_kms_crypto_key_iam_member.gke_agent]
}
