# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Destroy simulation: teardown order + the reference's `state rm` wart.

SURVEY §3.4: the reference requires `terraform state rm` of the operator
namespace before `terraform destroy` (/root/reference/gke/README.md:59).
These tests (a) reproduce that hazard class on a synthetic module shaped like
the reference, and (b) prove both of our modules plan hazard-free because the
depends_on chain gives Terraform the edge the reference is missing.
"""

import os
import textwrap

import pytest

from nvidia_terraform_modules_tpu.tfsim import (
    simulate_destroy,
    simulate_plan,
)

MODULE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GKE_VARS = {"project_id": "proj-x", "cluster_name": "demo"}
TPU_VARS = {"project_id": "proj-x", "cluster_name": "demo"}


def _write_module(tmp_path, main_tf: str) -> str:
    (tmp_path / "main.tf").write_text(textwrap.dedent(main_tf))
    return str(tmp_path)


WART_MODULE = """
    variable "name" {
      type    = string
      default = "demo"
    }

    resource "google_container_cluster" "c" {
      name = var.name
    }

    provider "kubernetes" {
      host = google_container_cluster.c.endpoint
    }

    resource "kubernetes_namespace_v1" "ns" {
      metadata {
        name = "operator"
      }
      %s
    }
"""


def test_reference_wart_is_flagged(tmp_path):
    """Namespace with no edge to the cluster → the `state rm` hazard."""
    path = _write_module(tmp_path, WART_MODULE % "")
    d = simulate_destroy(path, {})
    assert not d.ok
    (h,) = d.hazards
    assert h.resource == "kubernetes_namespace_v1.ns"
    assert h.provider == "kubernetes"
    assert h.missing_edges == ["google_container_cluster.c"]
    assert "state rm" in h.describe()


def test_depends_on_designs_the_wart_out(tmp_path):
    path = _write_module(
        tmp_path, WART_MODULE % "depends_on = [google_container_cluster.c]")
    d = simulate_destroy(path, {})
    assert d.ok, [h.describe() for h in d.hazards]
    # and the destroy order then respects the edge: namespace dies first
    assert d.order.index("kubernetes_namespace_v1.ns") < \
        d.order.index("google_container_cluster.c")


def test_destroy_order_is_reverse_apply(tmp_path):
    path = _write_module(tmp_path, """
        resource "google_compute_network" "net" {
          name = "n"
        }

        resource "google_compute_subnetwork" "sub" {
          network = google_compute_network.net.id
        }

        data "google_project" "p" {}
    """)
    d = simulate_destroy(path, {})
    assert d.order == [
        "google_compute_subnetwork.sub", "google_compute_network.net"]
    assert all(not a.startswith("data.") for a in d.order)


def test_gke_module_destroys_hazard_free():
    d = simulate_destroy(os.path.join(MODULE_DIR, "gke"), dict(GKE_VARS))
    assert d.ok, [h.describe() for h in d.hazards]
    # release → namespace → pool → cluster while the API server still exists
    idx = {a: i for i, a in enumerate(d.order)}
    assert idx["helm_release.gpu_operator"] < idx["kubernetes_namespace_v1.gpu_operator"]
    assert idx["kubernetes_namespace_v1.gpu_operator"] < idx["google_container_node_pool.gpu"]
    assert idx["google_container_node_pool.gpu"] < idx["google_container_cluster.this"]


def test_gke_tpu_module_destroys_hazard_free():
    d = simulate_destroy(os.path.join(MODULE_DIR, "gke-tpu"), dict(TPU_VARS))
    assert d.ok, [h.describe() for h in d.hazards]
    idx = {a: i for i, a in enumerate(d.order)}
    assert idx["helm_release.tpu_runtime"] < idx["kubernetes_namespace_v1.tpu_runtime"]
    assert idx["kubernetes_namespace_v1.tpu_runtime"] < idx["google_container_cluster.this"]


def test_existing_plan_can_be_reused(tmp_path):
    path = _write_module(tmp_path, WART_MODULE % "")
    plan = simulate_plan(path, {})
    d = simulate_destroy(path, {}, plan=plan)
    assert not d.ok


def test_aliased_provider_meta_arg_is_tracked(tmp_path):
    """`provider = kubernetes.gke` binds to the aliased config's needs."""
    path = _write_module(tmp_path, """
        resource "google_container_cluster" "c" {
          name = "x"
        }

        provider "kubernetes" {
          alias = "gke"
          host  = google_container_cluster.c.endpoint
        }

        resource "kubernetes_namespace_v1" "ns" {
          provider = kubernetes.gke
          metadata {
            name = "operator"
          }
        }
    """)
    d = simulate_destroy(path, {})
    assert not d.ok
    assert d.hazards[0].provider == "kubernetes.gke"


def test_statically_configured_alias_not_false_flagged(tmp_path):
    """A resource on a static aliased provider must not inherit the default
    provider's needs."""
    path = _write_module(tmp_path, """
        resource "google_container_cluster" "c" {
          name = "x"
        }

        provider "kubernetes" {
          host = google_container_cluster.c.endpoint
        }

        provider "kubernetes" {
          alias = "static"
          host  = "https://example.invalid"
        }

        resource "kubernetes_namespace_v1" "ns" {
          provider = kubernetes.static
          metadata {
            name = "operator"
          }
        }
    """)
    d = simulate_destroy(path, {})
    assert d.ok, [h.describe() for h in d.hazards]


def test_provider_config_through_local_is_tracked(tmp_path):
    """cluster attr routed through a local still counts as a provider need."""
    path = _write_module(tmp_path, """
        resource "google_container_cluster" "c" {
          name = "x"
        }

        locals {
          ep = google_container_cluster.c.endpoint
        }

        provider "kubernetes" {
          host = local.ep
        }

        resource "kubernetes_namespace_v1" "ns" {
          metadata {
            name = "operator"
          }
        }
    """)
    d = simulate_destroy(path, {})
    assert not d.ok
    assert d.hazards[0].missing_edges == ["google_container_cluster.c"]


def test_child_module_wart_detected_and_order_expanded(tmp_path):
    """A wart inside a local child module (the examples/cnpack idiom) is
    found, and the child's resources appear in the destroy order."""
    child = tmp_path / "child"
    child.mkdir()
    (child / "main.tf").write_text(textwrap.dedent(WART_MODULE % ""))
    (tmp_path / "main.tf").write_text(textwrap.dedent("""
        module "wrap" {
          source = "./child"
          name   = "demo"
        }
    """))
    d = simulate_destroy(str(tmp_path), {})
    assert not d.ok
    assert d.hazards[0].resource == "module.wrap.kubernetes_namespace_v1.ns"
    assert "module.wrap.google_container_cluster.c" in d.order


PARENT_PROVIDER_LAYOUT = """
    module "gke" {
      source = "./gke"
    }

    provider "kubernetes" {
      host = module.gke.endpoint
    }

    module "app" {
      source = "./app"
      %s
    }
"""


def _parent_provider_fixture(tmp_path, app_args=""):
    """Root configures the provider from module.gke; module.app consumes it —
    the cnpack idiom (provider in the example root, resources in the wrap)."""
    import textwrap
    for name, body in [
        ("gke", """
            resource "google_container_cluster" "c" {
              name = "x"
            }

            output "endpoint" {
              value = google_container_cluster.c.endpoint
            }
        """),
        ("app", """
            variable "dep" {
              type    = string
              default = ""
            }

            resource "kubernetes_namespace_v1" "ns" {
              metadata {
                name = "operator"
              }
            }
        """),
    ]:
        d = tmp_path / name
        d.mkdir()
        (d / "main.tf").write_text(textwrap.dedent(body))
    (tmp_path / "main.tf").write_text(
        textwrap.dedent(PARENT_PROVIDER_LAYOUT % app_args))
    return str(tmp_path)


def test_parent_provider_child_resource_wart_detected(tmp_path):
    path = _parent_provider_fixture(tmp_path)
    d = simulate_destroy(path, {})
    assert not d.ok
    (h,) = d.hazards
    assert h.resource == "module.app.kubernetes_namespace_v1.ns"
    assert h.missing_edges == ["module.gke"]


def test_parent_provider_protected_by_module_dependency(tmp_path):
    # wiring module.gke's output into module.app creates the ordering edge
    path = _parent_provider_fixture(tmp_path, "dep = module.gke.endpoint")
    d = simulate_destroy(path, {})
    assert d.ok, [h.describe() for h in d.hazards]
    assert d.order.index("module.app.kubernetes_namespace_v1.ns") < \
        d.order.index("module.gke.google_container_cluster.c")


def test_child_declared_provider_shadows_inherited(tmp_path):
    """A child module with its OWN provider block (even statically
    configured) must not inherit the parent's provider needs."""
    import textwrap
    for name, body in [
        ("gke", """
            resource "google_container_cluster" "c" {
              name = "x"
            }

            output "endpoint" {
              value = google_container_cluster.c.endpoint
            }
        """),
        ("app", """
            variable "host" {
              type    = string
              default = "https://static.invalid"
            }

            provider "kubernetes" {
              host = var.host
            }

            resource "kubernetes_namespace_v1" "ns" {
              metadata {
                name = "operator"
              }
            }
        """),
    ]:
        d = tmp_path / name
        d.mkdir()
        (d / "main.tf").write_text(textwrap.dedent(body))
    (tmp_path / "main.tf").write_text(textwrap.dedent("""
        module "gke" {
          source = "./gke"
        }

        provider "kubernetes" {
          host = module.gke.endpoint
        }

        module "app" {
          source = "./app"
        }
    """))
    d = simulate_destroy(str(tmp_path), {})
    assert d.ok, [h.describe() for h in d.hazards]


def test_cnpack_examples_destroy_hazard_free():
    for path in ("gke/examples/cnpack", "gke-tpu/examples/cnpack"):
        d = simulate_destroy(os.path.join(MODULE_DIR, path),
                             {"project_id": "proj-y"})
        assert d.ok, (path, [h.describe() for h in d.hazards])
        # the wrapped module's resources are part of the teardown walk
        assert any(".google_container_cluster.this" in a for a in d.order)
