# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Golden statefiles + the slice-pool-rename migration (VERDICT r1 item 9).

Two layers of protection:

1. Golden states: `tfsim apply` of the flagship module and its cnpack
   example is committed under tests/golden/. Any change to what gets
   planned — an address, an attribute, an ordering-visible value — shows
   up as a golden diff at review time instead of a surprise `terraform
   plan` against production state. Regenerate intentionally with
   ``GOLDEN_UPDATE=1 python -m pytest tests/test_state_golden.py``.

2. Moved-block migration for the riskiest real-world edit: renaming a
   ``tpu_slices`` map key re-keys ``google_container_node_pool.
   tpu_slice[...]`` — without care, terraform destroys and re-creates the
   slice pool. With a ``moved`` block and the slice's ``name`` override
   (pinning the deployed pool name), the rename must plan as a NO-OP.
"""

import json
import os
import shutil

import pytest

from nvidia_terraform_modules_tpu.tfsim import load_module, simulate_plan
from nvidia_terraform_modules_tpu.tfsim.state import (
    State,
    apply_plan,
    diff,
    migrate_state,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")

CASES = {
    "gke_tpu_default": ("gke-tpu", {
        "project_id": "golden-proj", "cluster_name": "golden"}),
    "gke_tpu_multislice": ("gke-tpu", {
        "project_id": "golden-proj", "cluster_name": "golden",
        "tpu_slices": {
            "train": {"version": "v4", "topology": "2x2x4"},
            "serve": {"version": "v5e", "topology": "2x2", "spot": True},
        },
        "smoketest": {"multislice": True},
    }),
    "cnpack_example": ("gke-tpu/examples/cnpack", {
        "project_id": "golden-proj"}),
}


def _apply(moddir: str, tfvars: dict) -> State:
    return apply_plan(simulate_plan(os.path.join(ROOT, moddir), tfvars))


@pytest.mark.parametrize("case", sorted(CASES))
def test_golden_state(case):
    moddir, tfvars = CASES[case]
    state = _apply(moddir, tfvars)
    path = os.path.join(GOLDEN, f"{case}.tfstate.json")
    got = json.loads(state.to_json())
    if os.environ.get("GOLDEN_UPDATE") == "1":
        os.makedirs(GOLDEN, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(got, fh, indent=2, sort_keys=True)
            fh.write("\n")
    with open(path) as fh:
        want = json.load(fh)
    assert got == want, (
        f"{case}: applied state drifted from tests/golden/{case}."
        f"tfstate.json — if the plan change is intentional, regenerate "
        f"with GOLDEN_UPDATE=1")


@pytest.mark.parametrize("case", sorted(CASES))
def test_golden_reapply_is_noop(case):
    """Idempotence against the committed artifact, not just in-memory."""
    moddir, tfvars = CASES[case]
    with open(os.path.join(GOLDEN, f"{case}.tfstate.json")) as fh:
        prior = State.from_json(fh.read())
    d = diff(simulate_plan(os.path.join(ROOT, moddir), tfvars), prior)
    assert d.is_noop, {a: act for a, act in d.actions.items()
                         if act != "no-op"}


# ------------------------------------------------- slice-pool key rename

POOL_OLD = 'google_container_node_pool.tpu_slice["default"]'
POOL_NEW = 'google_container_node_pool.tpu_slice["primary"]'

RENAME_VARS = {
    "project_id": "golden-proj", "cluster_name": "golden",
    # name override pins the deployed pool name the old key produced, so
    # the cloud resource itself is untouched by the refactor
    "tpu_slices": {"primary": {"name": "golden-default"}},
    # runtime/smoketest off keeps the scenario on the pool; the tmp module
    # copy would otherwise shift path.module inside the helm chart path
    "tpu_runtime": {"enabled": False},
    "smoketest": {"enabled": False},
}


def _module_copy_with_moved(tmp_path):
    dst = tmp_path / "gke-tpu"
    shutil.copytree(os.path.join(ROOT, "gke-tpu"), dst,
                    ignore=shutil.ignore_patterns("examples"))
    (dst / "moved.tf").write_text(
        'moved {\n'
        f'  from = google_container_node_pool.tpu_slice["default"]\n'
        f'  to   = google_container_node_pool.tpu_slice["primary"]\n'
        '}\n'
    )
    return str(dst)


def test_slice_rename_without_moved_recreates_pool(tmp_path):
    """The hazard the moved block exists for: key rename = destroy+create."""
    prior = _apply("gke-tpu", {
        "project_id": "golden-proj", "cluster_name": "golden",
        "tpu_runtime": {"enabled": False},
        "smoketest": {"enabled": False}})
    plan = simulate_plan(os.path.join(ROOT, "gke-tpu"), RENAME_VARS)
    d = diff(plan, prior)
    assert d.actions[POOL_OLD] == "delete"
    assert d.actions[POOL_NEW] == "create"


def test_slice_rename_with_moved_is_noop(tmp_path):
    """moved{} + name override: the refactor must not touch the pool."""
    prior = _apply("gke-tpu", {
        "project_id": "golden-proj", "cluster_name": "golden",
        "tpu_runtime": {"enabled": False},
        "smoketest": {"enabled": False}})
    moddir = _module_copy_with_moved(tmp_path)
    mod = load_module(moddir)
    migrated, renames = migrate_state(prior, mod)
    assert renames == [(POOL_OLD, POOL_NEW)]
    d = diff(simulate_plan(mod, RENAME_VARS), migrated)
    assert d.is_noop, {a: act for a, act in d.actions.items()
                        if act != "no-op"}
    assert d.actions[POOL_NEW] == "no-op"


def test_slice_rename_moved_without_name_override_updates_not_recreates(
        tmp_path):
    """Even without pinning the pool name, moved{} downgrades the rename
    from destroy+create to an in-place name update."""
    prior = _apply("gke-tpu", {
        "project_id": "golden-proj", "cluster_name": "golden",
        "tpu_runtime": {"enabled": False},
        "smoketest": {"enabled": False}})
    moddir = _module_copy_with_moved(tmp_path)
    mod = load_module(moddir)
    migrated, _ = migrate_state(prior, mod)
    plan = simulate_plan(mod, {
        "project_id": "golden-proj", "cluster_name": "golden",
        "tpu_slices": {"primary": {}},
        "tpu_runtime": {"enabled": False},
        "smoketest": {"enabled": False}})
    d = diff(plan, migrated)
    assert d.actions[POOL_NEW] == "update"
    assert "name" in d.changed_keys[POOL_NEW]  # (+node_config: the
    # slice-name label embeds the key too)
    assert POOL_OLD not in d.actions
