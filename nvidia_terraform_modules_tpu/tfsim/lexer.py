# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""HCL2 lexer: source text → token stream.

Covers the token inventory used by real-world Terraform modules: identifiers,
numbers, quoted strings with ``${...}`` interpolation left raw for the parser,
heredocs, comments (``#``, ``//``, ``/* */``), operators and punctuation.
"""

from __future__ import annotations

import dataclasses
import re


class HclLexError(SyntaxError):
    pass


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str      # IDENT NUMBER STRING HEREDOC OP NEWLINE EOF
    value: str
    line: int
    col: int

    def __repr__(self) -> str:  # compact for parser error messages
        return f"{self.kind}({self.value!r})@{self.line}"


_OPS = [
    "<<~", "<<", "=>", "==", "!=", "<=", ">=", "&&", "||", "...",
    "?", ":", "=", "{", "}", "[", "]", "(", ")", ",", ".", "*", "/", "%",
    "+", "-", "!", "<", ">",
]
_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_-]*")
_NUMBER_RE = re.compile(r"\d+(\.\d+)?([eE][+-]?\d+)?")


def tokenize(src: str, filename: str = "<hcl>") -> list[Token]:
    toks: list[Token] = []
    i, line, col = 0, 1, 1
    n = len(src)

    def err(msg: str):
        raise HclLexError(f"{filename}:{line}:{col}: {msg}")

    while i < n:
        c = src[i]
        # --- whitespace & newlines ---
        if c == "\n":
            toks.append(Token("NEWLINE", "\n", line, col))
            i += 1
            line += 1
            col = 1
            continue
        if c in " \t\r":
            i += 1
            col += 1
            continue
        # --- comments ---
        if c == "#" or src.startswith("//", i):
            while i < n and src[i] != "\n":
                i += 1
            continue
        if src.startswith("/*", i):
            end = src.find("*/", i + 2)
            if end < 0:
                err("unterminated block comment")
            skipped = src[i : end + 2]
            line += skipped.count("\n")
            i = end + 2
            continue
        # --- heredoc ---
        if src.startswith("<<", i):
            strip_indent = src.startswith("<<~", i) or src.startswith("<<-", i)
            j = i + (3 if strip_indent else 2)
            m = _IDENT_RE.match(src, j)
            if not m:
                err("heredoc marker expected")
            marker = m.group(0)
            body_start = src.find("\n", m.end())
            if body_start < 0:
                err("unterminated heredoc")
            body_start += 1
            end_re = re.compile(rf"^[ \t]*{re.escape(marker)}[ \t]*$", re.M)
            em = end_re.search(src, body_start)
            if not em:
                err(f"heredoc end marker {marker} not found")
            body = src[body_start : em.start()]
            if strip_indent:
                lines = body.split("\n")
                indents = [
                    len(l) - len(l.lstrip()) for l in lines if l.strip()
                ]
                pad = min(indents) if indents else 0
                body = "\n".join(l[pad:] if l.strip() else l for l in lines)
            toks.append(Token("HEREDOC", body, line, col))
            line += src.count("\n", i, em.end())
            i = em.end()
            # consume trailing newline of the marker line if present
            if i < n and src[i] == "\n":
                toks.append(Token("NEWLINE", "\n", line, col))
                i += 1
                line += 1
            col = 1
            continue
        # --- quoted string (interpolation kept raw) ---
        # A context stack tracks nesting: "str" = inside a quoted string,
        # "interp" = inside ${...} / %{...}, "brace" = bare { } within an
        # interpolation. This keeps `"${replace(var.a, "}", "x")}"` intact —
        # braces inside nested string literals don't close the interpolation.
        if c == '"':
            j = i + 1
            stack = ["str"]
            while j < n and stack:
                ch = src[j]
                top = stack[-1]
                if top == "str":
                    if ch == "\\":
                        j += 2
                        continue
                    if src.startswith("${", j) or src.startswith("%{", j):
                        stack.append("interp")
                        j += 2
                        continue
                    if ch == '"':
                        stack.pop()
                        j += 1
                        continue
                    if ch == "\n" and len(stack) == 1:
                        err("newline in string literal")
                    j += 1
                else:  # interp / brace
                    if ch == '"':
                        stack.append("str")
                        j += 1
                        continue
                    if ch == "{":
                        stack.append("brace")
                        j += 1
                        continue
                    if ch == "}":
                        stack.pop()
                        j += 1
                        continue
                    j += 1
            if stack:
                err("unterminated string")
            j -= 1  # j is one past the closing quote
            toks.append(Token("STRING", src[i + 1 : j], line, col))
            col += j - i + 1
            line += src.count("\n", i, j)
            i = j + 1
            continue
        # --- number ---
        if c.isdigit():
            m = _NUMBER_RE.match(src, i)
            toks.append(Token("NUMBER", m.group(0), line, col))
            col += m.end() - i
            i = m.end()
            continue
        # --- identifier / keyword ---
        if c.isalpha() or c == "_":
            m = _IDENT_RE.match(src, i)
            toks.append(Token("IDENT", m.group(0), line, col))
            col += m.end() - i
            i = m.end()
            continue
        # --- operators / punctuation ---
        for op in _OPS:
            if src.startswith(op, i):
                toks.append(Token("OP", op, line, col))
                i += len(op)
                col += len(op)
                break
        else:
            err(f"unexpected character {c!r}")
    toks.append(Token("EOF", "", line, col))
    return toks
