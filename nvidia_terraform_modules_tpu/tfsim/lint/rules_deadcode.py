# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Dead-code and drift lint rules.

Unused declarations are how module APIs rot: a variable nobody reads
still demands a value from every caller, a stale tfvars key silently
does nothing, and a lockfile pinning a provider nobody requires makes
`init` drift invisible. Each rule here answers "is this declaration
load-bearing?" from the module's own reference graph.
"""

from __future__ import annotations

import os

from .. import ast as A
from .engine import LintContext, rule


def _uses(ctx: LintContext):
    """Reference sets, computed once: var names, local names, data
    (type, name) pairs, and ``module.<call>.<output>`` pairs — split so
    a variable referenced ONLY by its own validation block still counts
    as unused (the validation dies with the variable)."""
    cached = getattr(ctx, "_deadcode_uses", None)
    if cached is not None:
        return cached
    var_uses: dict[str, set] = {}   # var name -> referencing contexts
    local_uses: set = set()
    data_uses: set = set()
    module_uses: set = set()

    def record(node, context: str):
        for t, bound in A.scoped_traversals(node):
            if t.root in bound:
                continue
            if t.root == "var" and t.ops and t.ops[0][0] == "attr":
                var_uses.setdefault(t.ops[0][1], set()).add(context)
            elif t.root == "local" and t.ops and t.ops[0][0] == "attr":
                local_uses.add(t.ops[0][1])
            elif t.root == "data" and len(t.ops) >= 2 and \
                    t.ops[0][0] == "attr" and t.ops[1][0] == "attr":
                data_uses.add((t.ops[0][1], t.ops[1][1]))
            elif t.root == "module" and t.ops and t.ops[0][0] == "attr":
                call = t.ops[0][1]
                out = next((op[1] for op in t.ops[1:] if op[0] == "attr"),
                           None)
                module_uses.add((call, out))

    for body in ctx.mod.files.values():
        for blk in body.blocks:
            if blk.type == "variable" and blk.labels:
                record(blk.body, f"variable:{blk.labels[0]}")
            else:
                record(blk, "config")
    cached = (var_uses, local_uses, data_uses, module_uses)
    ctx._deadcode_uses = cached
    return cached


@rule("unused-variable", severity="warning", family="dead-code",
      summary="variable is declared but never referenced")
def check_unused_variable(ctx: LintContext):
    var_uses, _, _, _ = _uses(ctx)
    for v in ctx.mod.variables.values():
        contexts = var_uses.get(v.name, set())
        if contexts - {f"variable:{v.name}"}:
            continue
        yield (f"{v.file}:{v.line}",
               f"variable {v.name!r} is never used — callers must still "
               f"satisfy it; remove it or wire it in")


def _local_sites(ctx: LintContext) -> dict[str, tuple[str, int]]:
    """local name → (file, line) of its definition (the Module model
    flattens locals and drops positions; recover them from the ASTs)."""
    sites: dict[str, tuple[str, int]] = {}
    for fname, body in ctx.mod.files.items():
        for blk in body.blocks:
            if blk.type != "locals":
                continue
            for attr in blk.body.attributes:
                sites.setdefault(attr.name, (fname, attr.line))
    return sites


@rule("unused-local", severity="warning", family="dead-code",
      summary="local value is declared but never referenced")
def check_unused_local(ctx: LintContext):
    _, local_uses, _, _ = _uses(ctx)
    sites = _local_sites(ctx)
    for name in ctx.mod.locals:
        if name in local_uses:
            continue
        fname, line = sites.get(name, ("locals", 0))
        yield (f"{fname}:{line}", f"local.{name} is never used")


@rule("unreferenced-data-source", severity="warning", family="dead-code",
      summary="data source is declared but never read")
def check_unreferenced_data(ctx: LintContext):
    _, _, data_uses, _ = _uses(ctx)
    for r in ctx.mod.data_sources.values():
        if (r.type, r.name) in data_uses:
            continue
        yield (f"{r.file}:{r.line}",
               f"{r.address} is never read — it still performs a live "
               f"API call every plan")


@rule("unknown-module-output", severity="error", family="dead-code",
      summary="reference to an output the child module does not declare")
def check_unknown_module_output(ctx: LintContext):
    _, _, _, module_uses = _uses(ctx)
    children = ctx.child_modules()
    # attribute each bad reference to every site that makes it; cheap
    # re-walk keyed by the (call, output) pairs that are actually bad
    bad = set()
    for call, out in module_uses:
        child = children.get(call)
        if child is None or out is None:
            continue
        if out not in child.outputs:
            bad.add((call, out))
    if not bad:
        return
    for fname, body in ctx.mod.files.items():
        for t, bound in A.scoped_traversals(body):
            if t.root != "module" or t.root in bound or not t.ops or \
                    t.ops[0][0] != "attr":
                continue
            call = t.ops[0][1]
            out = next((op[1] for op in t.ops[1:] if op[0] == "attr"), None)
            if (call, out) in bad:
                child = children[call]
                yield (f"{fname}:{t.line}",
                       f"module.{call} declares no output {out!r} "
                       f"(child module at "
                       f"{os.path.relpath(child.path, ctx.path)})")


@rule("unused-module-output", severity="info", family="dead-code",
      summary="child module output never read by this configuration")
def check_unused_module_output(ctx: LintContext):
    """Info-severity by design: a library module's outputs serve EVERY
    caller, so only the composition root can know an output is globally
    dead. The finding points at the call site so a root-config owner can
    prune the child's API deliberately."""
    _, _, _, module_uses = _uses(ctx)
    read = {(call, out) for call, out in module_uses}
    for name, child in ctx.child_modules().items():
        if child is None:
            continue
        mc = ctx.mod.module_calls[name]
        unread = [o for o in sorted(child.outputs)
                  if (name, o) not in read and (name, None) not in read]
        for o in unread:
            yield (f"{mc.file}:{mc.line}",
                   f"output {o!r} of module.{name} is never read by this "
                   f"configuration")


@rule("tfvars-unknown-key", severity="warning", family="dead-code",
      summary="tfvars key has no matching variable declaration")
def check_tfvars_keys(ctx: LintContext):
    for fname, body in ctx.tfvars_bodies():
        for attr in body.attributes:
            if attr.name not in ctx.mod.variables:
                yield (f"{fname}:{attr.line}",
                       f"tfvars key {attr.name!r} matches no declared "
                       f"variable — terraform ignores it silently")


@rule("lockfile-stale-provider", severity="warning", family="dead-code",
      summary="dependency lockfile pins a provider the module tree no "
              "longer requires")
def check_lockfile_stale(ctx: LintContext):
    from ..lockfile import REGISTRY
    from ..parser import parse_hcl

    lock = ".terraform.lock.hcl"
    if not os.path.isfile(os.path.join(ctx.path, lock)):
        return
    try:
        body = parse_hcl(ctx.text(lock), filename=lock)
        reqs = ctx.requirements()
    except (SyntaxError, ValueError, OSError):
        # SyntaxError: HclParseError/HclLexError subclass it
        return  # a broken lockfile/tree is init -check's finding, not ours
    for blk in body.blocks:
        if blk.type != "provider" or len(blk.labels) != 1:
            continue
        addr = blk.labels[0]
        source = addr.removeprefix(f"{REGISTRY}/")
        if source not in reqs:
            yield (f"{lock}:{blk.line}",
                   f"locked provider {addr} is required by no module in "
                   f"the tree — regenerate with `tfsim init`")
