# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""tfsim workspace + console verbs: per-env state, terraform.workspace, REPL.

Workspaces give one configuration several independent states (the
reference's "one module, many deployments" pattern, CLI-native); console is
the operator's expression probe. Both must honour tfsim's opt-in contract:
explicit ``-state`` workflows and existing CI runs see no behaviour change
until a workspace verb is used in a module dir.
"""

import json
import os
import textwrap

import pytest

from nvidia_terraform_modules_tpu.tfsim.__main__ import main


@pytest.fixture()
def mod(tmp_path):
    (tmp_path / "main.tf").write_text(textwrap.dedent("""\
        variable "base" {
          type    = string
          default = "app"
        }
        locals {
          name = "${var.base}-${terraform.workspace}"
        }
        resource "google_compute_network" "net" {
          name = local.name
        }
        output "name" {
          value = local.name
        }
        """))
    return str(tmp_path)


def _ws_state(mod, name):
    return os.path.join(mod, "terraform.tfstate.d", name,
                        "terraform.tfstate.json")


# ---- workspaces -----------------------------------------------------------

def test_workspace_lifecycle(mod, capsys):
    assert main(["workspace", "list", mod]) == 0
    assert capsys.readouterr().out.strip() == "* default"

    assert main(["workspace", "new", mod, "staging"]) == 0
    capsys.readouterr()
    assert main(["workspace", "show", mod]) == 0
    assert capsys.readouterr().out.strip() == "staging"

    assert main(["workspace", "select", mod, "default"]) == 0
    capsys.readouterr()
    assert main(["workspace", "list", mod]) == 0
    out = capsys.readouterr().out
    assert "* default" in out and "  staging" in out


def test_workspace_select_missing_errors(mod, capsys):
    assert main(["workspace", "select", mod, "nope"]) == 1
    assert "does not exist" in capsys.readouterr().err


def test_workspace_new_duplicate_errors(mod, capsys):
    assert main(["workspace", "new", mod, "dev"]) == 0
    assert main(["workspace", "new", mod, "dev"]) == 1
    assert "already exists" in capsys.readouterr().err


def test_workspace_state_isolation_and_interpolation(mod, capsys):
    """apply in each workspace writes its own statefile, and
    terraform.workspace flows into the planned values."""
    assert main(["workspace", "new", mod, "staging"]) == 0
    assert main(["apply", mod]) == 0
    assert os.path.exists(_ws_state(mod, "staging"))

    assert main(["workspace", "select", mod, "default"]) == 0
    assert main(["apply", mod]) == 0
    assert os.path.exists(os.path.join(mod, "terraform.tfstate.json"))
    capsys.readouterr()

    assert main(["output", "-state", _ws_state(mod, "staging"), "name"]) == 0
    assert json.loads(capsys.readouterr().out) == "app-staging"
    assert main(["output", "-state",
                 os.path.join(mod, "terraform.tfstate.json"), "name"]) == 0
    assert json.loads(capsys.readouterr().out) == "app-default"


def test_workspace_flag_overrides_selection(mod, capsys):
    assert main(["workspace", "new", mod, "prod"]) == 0
    assert main(["workspace", "select", mod, "default"]) == 0
    capsys.readouterr()
    assert main(["console", mod, "-workspace", "prod",
                 "-e", "terraform.workspace"]) == 0
    assert json.loads(capsys.readouterr().out) == "prod"


def test_workspace_opt_in_no_state_written_without_verbs(mod):
    """Until a workspace verb runs, apply keeps the legacy no-state mode."""
    assert main(["apply", mod]) == 0
    assert not os.path.exists(os.path.join(mod, "terraform.tfstate.json"))
    assert not os.path.exists(os.path.join(mod, ".tfsim"))


def test_workspace_delete_guards(mod, capsys):
    assert main(["workspace", "new", mod, "tmp"]) == 0
    # current workspace: refuse
    assert main(["workspace", "delete", mod, "tmp"]) == 1
    assert "current workspace" in capsys.readouterr().err
    assert main(["workspace", "select", mod, "default"]) == 0
    # default: refuse
    assert main(["workspace", "delete", mod, "default"]) == 1
    capsys.readouterr()
    # non-empty: refuse without -force
    assert main(["workspace", "select", mod, "tmp"]) == 0
    assert main(["apply", mod]) == 0
    assert main(["workspace", "select", mod, "default"]) == 0
    capsys.readouterr()
    assert main(["workspace", "delete", mod, "tmp"]) == 1
    assert "-force" in capsys.readouterr().err
    assert main(["workspace", "delete", mod, "tmp", "-force"]) == 0
    assert not os.path.exists(os.path.dirname(_ws_state(mod, "tmp")))


def test_workspace_plan_against_workspace_state_is_noop(mod, capsys):
    assert main(["workspace", "new", mod, "dev"]) == 0
    assert main(["apply", mod]) == 0
    capsys.readouterr()
    assert main(["plan", mod]) == 0
    assert "Plan: 0 to add, 0 to change, 0 to destroy." in \
        capsys.readouterr().out


# ---- console --------------------------------------------------------------

def test_console_expressions(mod, capsys):
    assert main(["console", mod,
                 "-e", "local.name",
                 "-e", "upper(var.base)",
                 "-e", "google_compute_network.net.name",
                 "-e", "[for i in range(3) : i * 2]"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert [json.loads(x) for x in lines] == [
        "app-default", "APP", "app-default", [0, 2, 4]]


def test_console_stdin(mod, capsys, monkeypatch):
    import io

    monkeypatch.setattr("sys.stdin",
                        io.StringIO("# comment\n\nlocal.name\nvar.base\n"))
    assert main(["console", mod]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert [json.loads(x) for x in lines] == ["app-default", "app"]


def test_console_computed_renders_placeholder(mod, capsys):
    assert main(["console", mod, "-e", "google_compute_network.net.id"]) == 0
    assert json.loads(capsys.readouterr().out) == "<computed>"


def test_console_error_continues_and_exits_one(mod, capsys):
    assert main(["console", mod, "-e", "var.nope", "-e", "var.base"]) == 1
    out = capsys.readouterr()
    assert json.loads(out.out) == "app"      # later expressions still ran
    assert "nope" in out.err


def test_console_var_override(mod, capsys):
    assert main(["console", mod, "-var", "base=svc",
                 "-e", "local.name"]) == 0
    assert json.loads(capsys.readouterr().out) == "svc-default"


def test_workspace_flag_typo_refuses(mod, capsys):
    """-workspace with an unknown name must error, not fork fresh state."""
    assert main(["workspace", "new", mod, "prod"]) == 0
    capsys.readouterr()
    assert main(["apply", mod, "-workspace", "prdo"]) == 1
    assert "does not exist" in capsys.readouterr().err
    assert not os.path.exists(_ws_state(mod, "prdo"))


def test_output_follows_workspace(mod, capsys):
    assert main(["workspace", "new", mod, "stg"]) == 0
    assert main(["apply", mod]) == 0
    capsys.readouterr()
    assert main(["output", "-dir", mod, "name"]) == 0
    assert json.loads(capsys.readouterr().out) == "app-stg"
    assert main(["output", "-dir", mod, "-workspace", "default", "name"]) == 1
    assert "apply first" in capsys.readouterr().err
    assert main(["output"]) == 2
    assert "-state FILE or -dir" in capsys.readouterr().err


def test_workspace_delete_stray_file_is_clean_error(mod, capsys):
    assert main(["workspace", "new", mod, "tmp"]) == 0
    assert main(["workspace", "select", mod, "default"]) == 0
    stray = os.path.join(mod, "terraform.tfstate.d", "tmp", "notes.txt")
    with open(stray, "w") as fh:
        fh.write("stray")
    capsys.readouterr()
    assert main(["workspace", "delete", mod, "tmp", "-force"]) == 1
    assert "could not remove" in capsys.readouterr().err
