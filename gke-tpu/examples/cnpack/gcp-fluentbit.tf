# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Log shipping for the platform's Fluent Bit DaemonSet.
#
# Capability parity with /root/reference/eks/examples/cnpack/aws-fluentbit.tf:9-27
# (CloudWatch agent policy attached to node IAM roles — note both attachments
# there target the GPU role; the CPU one is a copy-paste bug the survey calls
# out, SURVEY.md §2.4). Designed out here: ONE Workload-Identity-scoped log
# writer identity that every pool's Fluent Bit pod impersonates, plus a
# dedicated Cloud Logging bucket with bounded retention.

variable "fluentbit_enabled" {
  description = "Provision the Fluent Bit log-writer identity and log bucket."
  type        = bool
  default     = true
}

variable "log_retention_days" {
  description = "Retention of the dedicated cluster log bucket."
  type        = number
  default     = 30
}

resource "google_service_account" "fluentbit" {
  count = var.fluentbit_enabled ? 1 : 0

  project      = var.project_id
  account_id   = "tpu-fluentbit-${random_id.sa_suffix.hex}"
  display_name = "Fluent Bit log writer for ${var.cluster_name}"
}

resource "google_service_account_iam_member" "fluentbit_wi" {
  count = var.fluentbit_enabled ? 1 : 0

  service_account_id = google_service_account.fluentbit[count.index].name
  role               = "roles/iam.workloadIdentityUser"
  member             = "serviceAccount:${var.project_id}.svc.id.goog[${local.monitoring_namespace}/tpu-fluentbit]"
}

resource "google_project_iam_member" "fluentbit_log_writer" {
  count = var.fluentbit_enabled ? 1 : 0

  project = var.project_id
  role    = "roles/logging.logWriter"
  member  = "serviceAccount:${google_service_account.fluentbit[count.index].email}"
}

resource "google_logging_project_bucket_config" "cnpack" {
  count = var.fluentbit_enabled ? 1 : 0

  project        = var.project_id
  location       = "global"
  bucket_id      = "${var.cluster_name}-logs"
  retention_days = var.log_retention_days
  description    = "Cluster logs shipped by the ${var.cluster_name} Fluent Bit DaemonSet"
}

# Route this cluster's container logs into the bucket — without a sink the
# _Default sink would keep sending them to the _Default bucket and the
# retention knob above would govern an empty bucket.
resource "google_logging_project_sink" "cnpack" {
  count = var.fluentbit_enabled ? 1 : 0

  project     = var.project_id
  name        = "${var.cluster_name}-to-log-bucket"
  destination = "logging.googleapis.com/projects/${var.project_id}/locations/global/buckets/${google_logging_project_bucket_config.cnpack[count.index].bucket_id}"
  filter      = "resource.type=\"k8s_container\" AND resource.labels.cluster_name=\"${var.cluster_name}\""

  unique_writer_identity = true
}

# the sink's service-account identity needs write access on the bucket
resource "google_project_iam_member" "sink_bucket_writer" {
  count = var.fluentbit_enabled ? 1 : 0

  project = var.project_id
  role    = "roles/logging.bucketWriter"
  member  = google_logging_project_sink.cnpack[count.index].writer_identity
}
