# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The psum smoke test + optional deeper burn-in.

North-star behaviour (BASELINE.json): after ``terraform apply`` on ``gke-tpu``,
a Kubernetes Job runs this module on every host of the slice and asserts

1. the expected number of TPU chips is visible (device plugin + topology OK);
2. a ``psum`` all-reduce over all chips returns the participant count (ICI OK);

and, at deeper validation levels,

3. collective micro-probes on every mesh axis (all-gather, reduce-scatter,
   ring permute — the ring-attention primitive) pass and report bandwidth;
4. a few train steps of the sharded burn-in transformer run loss-decreasing.

Output is ONE JSON line on stdout per host; exit code 0 iff everything passed,
so the Terraform ``kubernetes_job`` with ``wait_for_completion = true`` turns
``terraform apply`` itself into the integration test (vs. the reference's
"wait ~5 minutes and kubectl get pods", ``/root/reference/gke/README.md:50``).

The reference analogue of level "burnin" does not exist — the GPU modules never
run a training workload (``/root/reference/CONTRIBUTING.md:56``: manual testing
only).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any


@dataclasses.dataclass
class SmokeResult:
    ok: bool
    checks: dict[str, Any]
    seconds: float

    def to_json(self) -> str:
        return json.dumps(
            {"ok": self.ok, "seconds": round(self.seconds, 3), **self.checks}
        )


def run_smoketest(
    expected_devices: int | None = None,
    level: str = "probes",
    env: dict[str, str] | None = None,
) -> SmokeResult:
    """Run the validation suite (telemetry-exporting wrapper).

    With ``TPU_TELEMETRY_DIR`` set (or a registry injected via
    ``telemetry.set_registry``) every instrumented layer the suite
    drives — per-step train latency/MFU, checkpoint save/restore,
    supervisor events — lands in the telemetry plane, and the artifacts
    (Perfetto ``trace.json``, Prometheus ``metrics.prom``,
    ``summary.txt``) are exported after the suite finishes, whatever its
    verdict; their paths ride the JSON contract under ``"telemetry"``.
    """
    from ..telemetry import get_registry

    result = _run_smoketest(expected_devices, level, env)
    reg = get_registry()
    if reg.enabled:
        try:
            result.checks["telemetry"] = reg.export()
        except (OSError, ValueError) as exc:
            # observability must never fail the validation verdict
            result.checks["telemetry_error"] = str(exc)
    return result


def _run_smoketest(
    expected_devices: int | None = None,
    level: str = "probes",
    env: dict[str, str] | None = None,
) -> SmokeResult:
    """Run the validation suite.

    ``level`` ∈ {"psum", "probes", "burnin", "full"} — each a superset of
    the previous. ``full`` adds the expert/pipeline fabric legs: an
    all-to-all probe over a real ``ep`` axis, a few MoE train steps
    (dispatch/combine all-to-alls), and a 2-stage pipeline train step
    (forward+backward through the stage ``ppermute``) — the two mesh axes
    the dense burn-in never exercises.
    """
    if level not in ("psum", "probes", "burnin", "full"):
        raise ValueError(
            f"unknown smoke-test level {level!r}: expected "
            f"psum|probes|burnin|full"
        )
    e = os.environ if env is None else env
    t0 = time.perf_counter()
    checks: dict[str, Any] = {"level": level}
    ok = True

    # preflight: graftlint over the installed runtime package, BEFORE
    # any device/backend touch — an ERROR-severity convention violation
    # (unseeded RNG, host sync in a wave loop, lock-order cycle) refuses
    # the chip session outright instead of burning quota to find it
    from ..analysis import run_graftlint

    # TPU_SMOKETEST_LINT_DIR redirects the scan (tests point it at a
    # synthetic tree; operators can point it at a vendored overlay)
    pkg_dir = e.get("TPU_SMOKETEST_LINT_DIR") or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    try:
        lint_errors = [str(f) for f in run_graftlint(pkg_dir)
                       if f.severity == "error"]
    except (OSError, ValueError) as exc:
        # an unreadable tree must not block a chip session by itself
        lint_errors = []
        checks["lint_runtime_error"] = str(exc)
    checks["lint_runtime_ok"] = not lint_errors
    if lint_errors:
        checks["lint_runtime_findings"] = lint_errors
        return SmokeResult(ok=False, checks=checks,
                           seconds=time.perf_counter() - t0)

    from ..parallel import (
        build_mesh,
        make_rules,
        maybe_initialize_distributed,
        plan_mesh,
    )
    from ..parallel.collectives import ALL_PROBES

    job = maybe_initialize_distributed(e)
    checks["process_id"] = job.process_id if job else 0
    checks["num_processes"] = job.num_processes if job else 1

    import jax

    n_dev = len(jax.devices())
    checks["devices"] = n_dev
    checks["device_kind"] = jax.devices()[0].device_kind
    if expected_devices is None and "TPU_SMOKETEST_EXPECTED_DEVICES" in e:
        expected_devices = int(e["TPU_SMOKETEST_EXPECTED_DEVICES"])
    if expected_devices is not None:
        checks["expected_devices"] = expected_devices
        if n_dev != expected_devices:
            checks["device_count_ok"] = False
            return SmokeResult(False, checks, time.perf_counter() - t0)
        checks["device_count_ok"] = True

    # 1. the north-star check: psum over ALL chips on a flat mesh
    flat = build_mesh(plan_mesh(n_dev, tp=1, sp=1, axis_names=("dp", "sp", "tp")))
    from ..parallel.collectives import psum_probe

    r = psum_probe(flat, axis="dp", n_elems=1 << 16)
    checks["psum_ok"] = r["ok"]
    checks["psum_participants"] = r["participants"]
    ok &= r["ok"]

    # DCN validation: with >1 slice (explicit TPU_SMOKETEST_SLICES, or device
    # metadata on real multi-slice), psum over the slice axis proves the
    # cross-slice path — the analogue of the reference's node-to-node SG rules
    # (/root/reference/eks/main.tf:28-49) actually carrying traffic. A bad
    # slice config must FAIL the JSON contract, not crash it.
    from ..parallel import build_multislice_mesh, dcn_slice_count, plan_multislice

    ms_mesh = None
    try:
        n_slices = int(e.get("TPU_SMOKETEST_SLICES", "0")) or dcn_slice_count()
        if n_slices > 1:
            ms_mesh = build_multislice_mesh(plan_multislice(n_dev, n_slices))
    except (ValueError, TypeError) as exc:
        checks["slices_error"] = str(exc)
        return SmokeResult(False, checks, time.perf_counter() - t0)
    if ms_mesh is not None and ok:
        checks["slices"] = n_slices
        r = psum_probe(ms_mesh, axis="slice", n_elems=1 << 14)
        checks["dcn_psum_ok"] = r["ok"]
        checks["dcn_psum_participants"] = r["participants"]
        ok &= r["ok"]
        # the hierarchy leg: ICI reduce-scatter → DCN psum on the 1/k
        # chunk → ICI all-gather — the gradient path an elastic resume
        # re-traces whenever the slice count changes
        from ..parallel.collectives import hierarchical_psum_probe

        r = hierarchical_psum_probe(ms_mesh, n_elems=1 << 14)
        checks["hier_psum_ok"] = r["ok"]
        checks["hier_psum_participants"] = r["participants"]
        ok &= r["ok"]

    if level in ("probes", "burnin", "full") and ok:
        mesh = ms_mesh if ms_mesh is not None else build_mesh(plan_mesh(n_dev))
        checks["mesh"] = dict(mesh.shape)
        for name, probe in ALL_PROBES.items():
            axis = {"psum": "dp", "all_gather": "tp", "reduce_scatter": "tp",
                    "ring_permute": "dp", "all_to_all": "ep"}[name]
            if mesh.shape.get(axis, 1) == 1:
                axis = "dp" if mesh.shape["dp"] > 1 else "tp"
            if mesh.shape[axis] == 1:
                continue
            pr = probe(mesh, axis=axis, n_elems=1 << 14)
            checks[f"{name}_ok"] = pr["ok"]
            checks[f"{name}_gibps"] = round(pr["bytes"] / max(pr["seconds"], 1e-9) / (1 << 30), 3)
            ok &= pr["ok"]

    if level in ("burnin", "full") and ok:
        from ..models import (
            BurnInConfig,
            CheckpointError,
            Checkpointer,
            SupervisedLoop,
            init_params,
            make_train_step,
            resilience_from_env,
            synthetic_batch,
        )

        mesh = ms_mesh if ms_mesh is not None else build_mesh(plan_mesh(n_dev))
        rules = make_rules(mesh)
        data_shards = mesh.shape["dp"] * mesh.shape.get("slice", 1)
        cfg = BurnInConfig(batch=max(8, 2 * data_shards))

        # preemption resume: a spot slice's Job pod restarts mid-burn-in and
        # must continue from its last checkpoint, not start over (the module
        # provisions spot slices first-class — gke-tpu/tpu_slices.tf; the
        # Job wires a PVC mount or gs:// prefix via smoketest.checkpoint_dir).
        # The loop runs SUPERVISED (models/resilience.py): every step
        # checkpoints durably, a SIGTERM/preemption notice drains the
        # in-flight step and commits an emergency checkpoint inside the
        # grace budget (TPU_SMOKETEST_GRACE_SECONDS), heartbeat files
        # turn a dead peer's collective hang into a classified failure,
        # and a corrupt/truncated checkpoint is quarantined (reported in
        # checkpoint_quarantined) with restore falling back to the
        # newest valid step. A SUCCESSFUL run clears the directory so
        # the next fresh Job starts at step 0. Checkpoint I/O failure
        # still fails the suite through the JSON contract (never a bare
        # traceback): a broken resume path on spot capacity is an
        # operational bug.
        ckpt_dir = e.get("TPU_SMOKETEST_CHECKPOINT_DIR")
        ckpt = Checkpointer(ckpt_dir) if ckpt_dir else None
        rcfg = resilience_from_env(e)
        global_step = 0
        params = None
        try:
            if ckpt is not None:
                try:
                    restored = ckpt.restore(cfg, rules)
                except Exception as exc:  # storage-level failures only:
                    #  corruption falls back inside restore; the JSON
                    #  contract > the exception type
                    checks["burnin_checkpoint_ok"] = False
                    checks["checkpoint_error"] = f"restore: {exc}"
                    return SmokeResult(
                        False, checks, time.perf_counter() - t0)
                quarantined = ckpt.quarantined()
                if quarantined:
                    checks["checkpoint_quarantined"] = len(quarantined)
                if restored is not None:
                    params, global_step, _meta = restored
                    checks["burnin_resumed_step"] = global_step
            if params is None:
                params = init_params(jax.random.PRNGKey(0), cfg, rules)
            # per-step latency histogram + live tokens/s + MFU gauges
            # land in the telemetry plane (no-op unless enabled); the
            # loop below syncs per step via float(loss) anyway, so the
            # instrumented sync costs nothing extra here
            from ..models.burnin import instrument_step

            step = instrument_step(make_train_step(cfg, rules), cfg,
                                   rules=rules)
            batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
            losses = []

            def one_step(p, _step_no):
                p, loss = step(p, batch)
                losses.append(float(loss))
                return p

            # gs://… checkpoint prefixes have no filesystem for
            # heartbeat files — checkpoint.py owns the predicate
            from ..models.checkpoint import _is_remote

            loop = SupervisedLoop(
                ckpt, rcfg,
                total_steps=global_step + 5,
                process_id=job.process_id if job else 0,
                num_processes=job.num_processes if job else 1,
                heartbeat_dir=ckpt_dir if ckpt_dir and
                not _is_remote(ckpt_dir) else None,
            )
            try:
                params, outcome = loop.run(
                    params, one_step, start_step=global_step,
                    meta=lambda s, _p: {"last_loss": losses[-1]})
            except (CheckpointError, OSError) as exc:
                # storage-layer failures only (unwritable PVC, bounded
                # rendezvous timeout): a broken resume path is an
                # operational bug, reported as such. Train-step/XLA
                # errors propagate — blaming them on the checkpoint
                # engine would send the operator down the wrong path.
                checks["burnin_checkpoint_ok"] = False
                checks["checkpoint_error"] = f"save: {exc}"
                checks["burnin_step"] = global_step + len(losses)
                return SmokeResult(False, checks, time.perf_counter() - t0)
            if outcome is not None:
                global_step = outcome.step
                if outcome.status == "preempted":
                    # drained + emergency checkpoint committed: the Job
                    # controller restarts the pod and the next attempt
                    # resumes — report the classified state, not success
                    checks["burnin_preempted"] = global_step
                    checks["burnin_ok"] = False
                    return SmokeResult(
                        False, checks, time.perf_counter() - t0)
            if ckpt is not None and ok:
                checks["burnin_checkpoint_saved"] = global_step
            checks["burnin_first_loss"] = round(losses[0], 4)
            checks["burnin_last_loss"] = round(losses[-1], 4)
            checks["burnin_step"] = global_step
            checks["burnin_ok"] = (
                len(losses) == 5 and losses[-1] < losses[0])
            ok &= checks["burnin_ok"]

            # serve shape: a short greedy KV-cache decode on the trained
            # weights — proves the inference path (prefill + cached scan,
            # tp-sharded cache) on the same fresh slice, and that decode
            # is self-consistent with the training forward (greedy tokens
            # equal full re-forward argmax for the dense config)
            if checks["burnin_ok"]:
                from ..models import forward, greedy_decode

                try:
                    # full training batch rows: sized max(8, 2·data_shards)
                    # above, so the prompt's batch dim always divides the
                    # data sharding — a hardcoded small batch would crash
                    # exactly on the larger slices this Job targets
                    prompt = batch[0][:, :8]
                    toks = greedy_decode(params, prompt, 4, cfg, rules)
                    logits = forward(params, prompt, cfg, rules)
                    first_ref = jax.numpy.argmax(logits[:, -1], axis=-1)
                    # reduce to a replicated SCALAR before fetching: in a
                    # multi-host world the batch-sharded token array spans
                    # non-addressable devices and device_get would throw
                    match = jax.numpy.all(toks[:, 0] == first_ref)
                    checks["decode_ok"] = (
                        toks.shape == (prompt.shape[0], 4)
                        and bool(jax.device_get(match)))
                except Exception as exc:  # JSON contract > the type
                    checks["decode_ok"] = False
                    checks["decode_error"] = str(exc)
                ok &= checks["decode_ok"]

            # continuous-batching serve engine: the paged-KV scheduler
            # (models/serving.py) on a recycling schedule (5 requests
            # through 2 slots, ragged lengths) must bit-match solo
            # greedy decode per request — proves the serving runtime,
            # block allocation/recycling included, on the same fresh
            # slice. Tiny, unsharded and process-local on purpose: no
            # collectives, so every host validates independently and
            # the check is multi-controller-safe at any world size.
            if checks.get("decode_ok"):
                try:
                    from ..models import greedy_decode
                    from ..models.serving import make_serve_engine

                    ecfg = BurnInConfig(
                        vocab=128, d_model=32, n_heads=4, d_ff=64,
                        n_layers=2, seq_len=16, batch=2,
                        dtype=jax.numpy.float32)
                    eparams = init_params(jax.random.PRNGKey(8), ecfg)
                    eprompts = [
                        jax.random.randint(jax.random.PRNGKey(20 + i),
                                           (4 + (i % 3) * 2,), 0,
                                           ecfg.vocab)
                        for i in range(5)
                    ]
                    engine = make_serve_engine(eparams, ecfg,
                                               max_len=16, kv_block=4)
                    outs = engine(eprompts, 6, slots=2)
                    match = all(
                        bool(jax.device_get(jax.numpy.array_equal(
                            o, greedy_decode(eparams, p[None, :], 6,
                                             ecfg)[0])))
                        for o, p in zip(outs, eprompts))
                    kv = engine.last_stats["kv"]
                    checks["serve_engine_ok"] = match
                    checks["serve_engine_kv_peak_blocks"] = \
                        kv["high_water"]
                    checks["serve_engine_kv_utilisation"] = \
                        kv["utilisation"]
                except Exception as exc:  # JSON contract > the type
                    checks["serve_engine_ok"] = False
                    checks["serve_engine_error"] = str(exc)
                ok &= checks["serve_engine_ok"]

            # serve scheduler levers: cross-request prefix sharing +
            # lazy block growth are contractually SCHEDULING — shared
            # blocks and per-wave table growth must not change a single
            # token — so a tiny shared-prefix workload through the
            # lever engine must BIT-match the baseline engine (and
            # policy="fifo" must BE the baseline), on this slice's real
            # lowering. Mirrors flash_pipeline_ok: gate the scheduler
            # rewrite on chip before a serving job trusts it. Tiny,
            # unsharded, process-local (no collectives — every host
            # validates independently at any world size).
            if checks.get("serve_engine_ok"):
                try:
                    from ..models.serving import make_serve_engine
                    from ..utils.traffic import shared_prefix_prompts

                    scfg = BurnInConfig(
                        vocab=128, d_model=32, n_heads=4, d_ff=64,
                        n_layers=2, seq_len=16, batch=2,
                        dtype=jax.numpy.float32)
                    sparams = init_params(jax.random.PRNGKey(11), scfg)
                    pairs = shared_prefix_prompts(
                        5, seed=0, n_templates=2, template_len=9,
                        suffix_lo=1, suffix_hi=4, vocab=scfg.vocab)
                    sprompts = [jax.numpy.asarray(p, jax.numpy.int32)
                                for _t, p in pairs]
                    sbudgets = [2, 5, 1, 4, 3]
                    sml = max(int(p.shape[-1]) + n
                              for p, n in zip(sprompts, sbudgets))
                    base = make_serve_engine(sparams, scfg, max_len=sml,
                                             kv_block=4, policy="fifo")
                    b_outs = base(sprompts, sbudgets, slots=2)
                    lever = make_serve_engine(sparams, scfg, max_len=sml,
                                              kv_block=4,
                                              share_prefix=True,
                                              lazy_growth=True)
                    l_outs = lever(sprompts, sbudgets, slots=2)
                    match = all(
                        bool(jax.device_get(jax.numpy.array_equal(a, b)))
                        for a, b in zip(l_outs, b_outs))
                    st = lever.last_stats
                    checks["serve_sched_ok"] = (
                        match and st["prefix"]["hit_blocks"] > 0
                        and st["kv"]["in_use"] == 0)
                    checks["serve_sched_prefix_hit_blocks"] = \
                        st["prefix"]["hit_blocks"]
                    checks["serve_sched_blocks_grown_lazy"] = \
                        st["kv"]["blocks_grown_lazy"]
                except Exception as exc:  # JSON contract > the type
                    checks["serve_sched_ok"] = False
                    checks["serve_sched_error"] = str(exc)
                ok &= checks["serve_sched_ok"]

            # paged decode kernel gate: the block-table-native pallas
            # wave step (decode.forward_paged paged_kernel="on") is
            # contractually a READ-PATH change — same tables, same
            # liveness mask, no logical-view gather — so one
            # shared-prefix serving wave through the kernel engine
            # must BIT-match the gather engine's tokens on this
            # slice's real lowering. Mirrors flash_pipeline_ok: gate
            # the kernel rewrite on chip before a serving job trusts
            # it. Tiny, unsharded, process-local (no collectives —
            # every host validates independently at any world size).
            if checks.get("serve_sched_ok"):
                try:
                    from ..models.serving import make_serve_engine
                    from ..utils.traffic import shared_prefix_prompts

                    kcfg = BurnInConfig(
                        vocab=128, d_model=32, n_heads=4, d_ff=64,
                        n_layers=2, seq_len=16, batch=2,
                        dtype=jax.numpy.float32)
                    kparams = init_params(jax.random.PRNGKey(12), kcfg)
                    kpairs = shared_prefix_prompts(
                        4, seed=1, n_templates=2, template_len=9,
                        suffix_lo=1, suffix_hi=4, vocab=kcfg.vocab)
                    kprompts = [jax.numpy.asarray(p, jax.numpy.int32)
                                for _t, p in kpairs]
                    kbudgets = [3, 5, 2, 4]
                    kml = max(int(p.shape[-1]) + n
                              for p, n in zip(kprompts, kbudgets))
                    outs = {}
                    for mode in ("off", "on"):
                        eng = make_serve_engine(
                            kparams, kcfg, max_len=kml, kv_block=8,
                            share_prefix=True, paged_kernel=mode)
                        outs[mode] = eng(kprompts, kbudgets, slots=2)
                    checks["paged_decode_ok"] = all(
                        bool(jax.device_get(jax.numpy.array_equal(a, b)))
                        for a, b in zip(outs["on"], outs["off"]))
                except Exception as exc:  # JSON contract > the type
                    checks["paged_decode_ok"] = False
                    checks["paged_decode_error"] = str(exc)
                ok &= checks["paged_decode_ok"]

            # fleet router gate: the multi-engine router
            # (models/fleet.py) is contractually SCHEDULING — affinity
            # placement, per-replica queues and the thread-per-replica
            # execution must not change a single token — so a 2-replica
            # affinity fleet on a shared-prefix wave must BIT-match the
            # single-engine baseline, on this slice's real lowering,
            # with the router demonstrably routing (every request
            # placed by affinity) and both pools drained. Mirrors
            # serve_sched_ok: gate the fleet layer on chip before a
            # serving job trusts it. Tiny, process-local (replica
            # threads, no collectives — every host validates
            # independently at any world size).
            if checks.get("paged_decode_ok"):
                try:
                    from ..models.fleet import make_fleet
                    from ..models.serving import make_serve_engine
                    from ..utils.traffic import shared_prefix_prompts

                    fcfg = BurnInConfig(
                        vocab=128, d_model=32, n_heads=4, d_ff=64,
                        n_layers=2, seq_len=16, batch=2,
                        dtype=jax.numpy.float32)
                    fparams = init_params(jax.random.PRNGKey(13), fcfg)
                    fpairs = shared_prefix_prompts(
                        6, seed=2, n_templates=2, template_len=8,
                        suffix_lo=1, suffix_hi=4, vocab=fcfg.vocab)
                    fprompts = [jax.numpy.asarray(p, jax.numpy.int32)
                                for _t, p in fpairs]
                    fbudgets = [3, 5, 2, 4, 3, 2]
                    fml = max(int(p.shape[-1]) + n
                              for p, n in zip(fprompts, fbudgets))
                    base = make_serve_engine(fparams, fcfg, max_len=fml,
                                             kv_block=4,
                                             share_prefix=True)
                    b_outs = base(fprompts, fbudgets, slots=2)
                    fleet = make_fleet(fparams, fcfg, max_len=fml,
                                       replicas=2, kv_block=4,
                                       share_prefix=True, steal=False)
                    f_outs = fleet(fprompts, fbudgets, slots=2)
                    match = all(
                        o is not None
                        and bool(jax.device_get(
                            jax.numpy.array_equal(o, b)))
                        for o, b in zip(f_outs, b_outs))
                    fst = fleet.last_stats["fleet"]
                    drained = all(
                        rs["kv"]["in_use"] == 0
                        for rs in fleet.last_stats["replica_stats"])
                    checks["serve_fleet_ok"] = (
                        match and fst["shed"] == 0
                        and fst["affinity_routed_frac"] == 1.0
                        and drained)
                    checks["serve_fleet_hit_blocks"] = \
                        fst["affinity_hit_blocks"]
                    checks["serve_fleet_replicas"] = fst["replicas"]
                except Exception as exc:  # JSON contract > the type
                    checks["serve_fleet_ok"] = False
                    checks["serve_fleet_error"] = str(exc)
                ok &= checks["serve_fleet_ok"]

            # fleet chaos gate (PR 13): the fault plane's burn-in leg.
            # A 3-replica fleet with a SEEDED mid-wave replica kill
            # must still bit-match the single-engine baseline on EVERY
            # completed request — the health monitor declares the
            # victim dead, its queued and in-flight requests redrive
            # to survivors by re-admission (tokens are schedule-
            # invariant, so recovery is exact, not best-effort), and
            # the survivors' pools drain to zero. This is the serving
            # twin of the training chaos gate (smoketest/chaos.py):
            # gate the recovery runtime on this slice's real lowering
            # before a preemptible serving pool trusts it. Reuses the
            # serve_fleet wave + baseline above.
            if checks.get("serve_fleet_ok"):
                try:
                    from ..models.fleet import (
                        FleetFault,
                        FleetFaultProfile,
                        HashRing,
                        affinity_key,
                    )

                    # kill the replica the FIRST prompt routes to — a
                    # target guaranteed to own work on this wave
                    victim = HashRing(3).target(
                        affinity_key(fprompts[0], 4))
                    chaos = make_fleet(
                        fparams, fcfg, max_len=fml, replicas=3,
                        kv_block=4, share_prefix=True, steal=False,
                        faults=FleetFaultProfile(
                            [FleetFault("kill_replica", target=victim,
                                        at_s=0.05)],
                            seed=0))
                    c_outs = chaos(fprompts, fbudgets, slots=2)
                    c_match = all(
                        o is not None
                        and bool(jax.device_get(
                            jax.numpy.array_equal(o, b)))
                        for o, b in zip(c_outs, b_outs))
                    cst = chaos.last_stats["fleet"]
                    c_drained = all(
                        rs["kv"]["in_use"] == 0
                        for rs in chaos.last_stats["replica_stats"]
                        if rs is not None)
                    checks["fleet_chaos_ok"] = (
                        c_match and cst["served"] == len(fprompts)
                        and cst["shed"] == 0
                        and cst["faults"]["replica_down"] == 1
                        and c_drained)
                    checks["fleet_chaos_redriven"] = \
                        cst["faults"]["redriven"]
                except Exception as exc:  # JSON contract > the type
                    checks["fleet_chaos_ok"] = False
                    checks["fleet_chaos_error"] = str(exc)
                ok &= checks["fleet_chaos_ok"]

            # tiered-KV gate (ISSUE 14): the host-RAM spill tier
            # (models/hostkv.py behind the prefix index) is
            # contractually a CACHING change — a spilled chain swapped
            # back in restores the exact exported bytes — so a
            # tight-kv_blocks spilling engine on a template wave that
            # OVERFLOWS the device keep-cap must BIT-match the
            # unconstrained no-spill baseline, with ≥ 1 swap-in
            # actually observed (a wave that never crossed the tier
            # proves nothing) and BOTH pools drained. Gates the
            # host↔HBM staging path on this host's real allocator/
            # transfer lowering before a serving job trusts it. Tiny,
            # process-local (one engine, no collectives).
            if checks.get("serve_sched_ok"):
                try:
                    from ..models.serving import make_serve_engine
                    from ..utils.traffic import shared_prefix_prompts

                    vcfg = BurnInConfig(
                        vocab=128, d_model=32, n_heads=4, d_ff=64,
                        n_layers=2, seq_len=16, batch=2,
                        dtype=jax.numpy.float32)
                    vparams = init_params(jax.random.PRNGKey(14), vcfg)
                    # working_set_blocks > prefix_keep_blocks=0: every
                    # retirement evicts, so sequential repeats MUST
                    # come back through the host tier
                    vpairs = shared_prefix_prompts(
                        6, seed=3, template_len=8, suffix_lo=1,
                        suffix_hi=4, vocab=vcfg.vocab,
                        working_set_blocks=4, block_size=4)
                    vprompts = [jax.numpy.asarray(p, jax.numpy.int32)
                                for _t, p in vpairs]
                    vbudgets = [3, 4, 2, 4, 3, 2]
                    vml = max(int(p.shape[-1]) + n
                              for p, n in zip(vprompts, vbudgets))
                    vbase = make_serve_engine(vparams, vcfg,
                                              max_len=vml, kv_block=4)
                    v_outs = vbase(vprompts, vbudgets, slots=1)
                    vtight = 1 + -(-vml // 4) + 2
                    spill = make_serve_engine(
                        vparams, vcfg, max_len=vml, kv_block=4,
                        share_prefix=True, prefix_keep_blocks=0,
                        host_spill=True)
                    s_outs = spill(vprompts, vbudgets, slots=1,
                                   kv_blocks=vtight)
                    s_match = all(
                        bool(jax.device_get(
                            jax.numpy.array_equal(a, b)))
                        for a, b in zip(s_outs, v_outs))
                    sp = spill.last_stats["prefix"]["spill"]
                    checks["kv_spill_ok"] = (
                        s_match and sp["swapins"] >= 1
                        and sp["spilled_blocks"] > 0
                        and sp["corrupt_dropped"] == 0
                        and spill.last_stats["kv"]["in_use"] == 0
                        and sp["host_in_use"] == 0)
                    checks["kv_spill_swapins"] = sp["swapins"]
                    checks["kv_spill_spilled_blocks"] = \
                        sp["spilled_blocks"]
                except Exception as exc:  # JSON contract > the type
                    checks["kv_spill_ok"] = False
                    checks["kv_spill_error"] = str(exc)
                ok &= checks["kv_spill_ok"]

            # elastic-fleet gate (ISSUE 15): the autoscaler is
            # contractually a PLACEMENT change — replicas joining and
            # draining at runtime move work, never bits — so a seeded
            # scale-up→churn→scale-down run (a burst joins a replica,
            # the sparse tail drains the base one, which publishes its
            # working set) must BIT-match the single-engine baseline,
            # and a SECOND identical run must replay the same schedule
            # with the joiner inheriting the published chains WARM
            # (host-tier seeds converting to real prefix hits). Gates
            # warm bring-up on this slice's real lowering before a
            # preemptible serving pool rides the autoscaler. Reuses
            # the fleet gate's config; tiny, process-local.
            if checks.get("fleet_chaos_ok"):
                try:
                    from ..models.fleet import AutoscalePolicy

                    spairs = shared_prefix_prompts(
                        12, seed=6, n_templates=4, template_len=8,
                        suffix_lo=1, suffix_hi=4, vocab=fcfg.vocab)
                    sprompts = [jax.numpy.asarray(p, jax.numpy.int32)
                                for _t, p in spairs]
                    sbudgets = [3, 4, 2, 4, 3, 2, 4, 3, 2, 3, 4, 2]
                    sml = max(int(p.shape[-1]) + n
                              for p, n in zip(sprompts, sbudgets))
                    sbase = make_serve_engine(fparams, fcfg,
                                              max_len=sml, kv_block=4,
                                              share_prefix=True)
                    sb_outs = sbase(sprompts, sbudgets, slots=2)
                    sarr = [0.0] * 8 + [0.5 + 0.25 * i
                                        for i in range(4)]
                    elastic = make_fleet(
                        fparams, fcfg, max_len=sml, replicas=1,
                        kv_block=4, share_prefix=True, host_spill=True,
                        host_blocks=64, prefix_keep_blocks=16,
                        est_token_s=0.02, steal=False,
                        autoscale=AutoscalePolicy(
                            min_replicas=1, max_replicas=3,
                            up_backlog=2.0, down_backlog=0.5,
                            cooldown_s=0.05, seed=0))
                    rounds = []
                    for _ in range(2):
                        e_outs = elastic(sprompts, sbudgets, slots=2,
                                         arrivals=sarr)
                        est = elastic.last_stats["fleet"]
                        reps = elastic.last_stats["replica_stats"]
                        rounds.append({
                            "match": all(
                                o is not None
                                and bool(jax.device_get(
                                    jax.numpy.array_equal(o, b)))
                                for o, b in zip(e_outs, sb_outs)),
                            "scale": est["scale"],
                            "drained": all(
                                rs["kv"]["in_use"] == 0
                                and rs["prefix"]["spill"]
                                ["host_in_use"] == 0
                                for rs in reps if rs is not None),
                            "joiner_hits": sum(
                                rs["prefix"]["hit_blocks"]
                                for i, rs in enumerate(reps)
                                if rs is not None
                                and i >= est["scale"]["initial"]),
                            "warm_blocks": sum(
                                rs["prefix"]["warm"]["seeded_blocks"]
                                for rs in reps if rs is not None),
                        })
                    r1, r2 = rounds
                    checks["fleet_scale_ok"] = (
                        r1["match"] and r2["match"]
                        and r1["drained"] and r2["drained"]
                        and r1["scale"]["ups_executed"] >= 1
                        and r1["scale"]["downs"] >= 1
                        # same trace ⇒ same schedule, replayed
                        and r2["scale"]["events"]
                        == r1["scale"]["events"]
                        # round 2's joiner inherited WARM and the
                        # seeds converted to real prefix hits
                        and r2["scale"]["warm_joins"] >= 1
                        and r2["warm_blocks"] >= 1
                        and r2["joiner_hits"] > 0)
                    checks["fleet_scale_warm_blocks"] = \
                        r2["warm_blocks"]
                    checks["fleet_scale_joiner_hits"] = \
                        r2["joiner_hits"]
                except Exception as exc:  # JSON contract > the type
                    checks["fleet_scale_ok"] = False
                    checks["fleet_scale_error"] = str(exc)
                ok &= checks["fleet_scale_ok"]

            # cold-start gate (ISSUE 19): the AOT compile cache
            # (models/aotcache.py) is contractually a COMPILE-TIME
            # change — cached executables and a primed call path,
            # never different bits — so a warmed engine on a shared-
            # prefix wave must BIT-match the plain cold engine, and a
            # SECOND bring-up against the same cache dir must land
            # real probe hits (> 0) on this backend's serialization
            # support (or its trace-only demotion). Gates the
            # persistent cache on this host's real XLA before a
            # fleet's joiners trust it for second-scale bring-up.
            # Tiny, process-local; the cache dir is torn down and
            # DEACTIVATED so later legs compile against the default
            # config untouched.
            if checks.get("serve_sched_ok"):
                try:
                    import shutil
                    import tempfile

                    from ..models.serving import make_serve_engine
                    from ..utils.traffic import shared_prefix_prompts

                    acfg = BurnInConfig(
                        vocab=128, d_model=32, n_heads=4, d_ff=64,
                        n_layers=2, seq_len=16, batch=2,
                        dtype=jax.numpy.float32)
                    aparams = init_params(jax.random.PRNGKey(21),
                                          acfg)
                    apairs = shared_prefix_prompts(
                        6, seed=5, template_len=8, suffix_lo=1,
                        suffix_hi=4, vocab=acfg.vocab)
                    aprompts = [jax.numpy.asarray(p, jax.numpy.int32)
                                for _t, p in apairs]
                    abudgets = [3, 4, 2, 4, 3, 2]
                    aml = max(int(p.shape[-1]) + n
                              for p, n in zip(aprompts, abudgets))
                    alens = tuple(sorted(
                        {int(p.shape[-1]) for p in aprompts}))
                    acold = make_serve_engine(
                        aparams, acfg, max_len=aml, kv_block=4,
                        share_prefix=True)
                    a_outs = acold(aprompts, abudgets, slots=2)
                    adir = tempfile.mkdtemp(prefix="smoke_aot_")
                    try:
                        aw1 = make_serve_engine(
                            aparams, acfg, max_len=aml, kv_block=4,
                            share_prefix=True, aot_cache=adir)
                        ws1 = aw1.warm(slots=2, prompt_lens=alens,
                                       n_new=max(abudgets))
                        w_outs = aw1(aprompts, abudgets, slots=2)
                        a_match = all(
                            bool(jax.device_get(
                                jax.numpy.array_equal(a, b)))
                            for a, b in zip(w_outs, a_outs))
                        aw2 = make_serve_engine(
                            aparams, acfg, max_len=aml, kv_block=4,
                            share_prefix=True, aot_cache=adir)
                        ws2 = aw2.warm(slots=2, prompt_lens=alens,
                                       n_new=max(abudgets))
                        checks["aot_warm_ok"] = (
                            a_match
                            and ws1["enabled"] and ws2["enabled"]
                            and ws1["registered"] >= 1
                            and not ws1["errors"]
                            and not ws2["errors"]
                            and ws2["hits"] >= 1)
                        checks["aot_warm_registered"] = \
                            ws1["registered"]
                        checks["aot_warm_second_hits"] = ws2["hits"]
                        # restore the jax cache config in reverse
                        # activation order (activate is sticky by
                        # design — joiners keep compiling into the
                        # fleet's dir — so the smoke leg unwinds it)
                        aw2.aot_cache.deactivate()
                        aw1.aot_cache.deactivate()
                    finally:
                        shutil.rmtree(adir, ignore_errors=True)
                except Exception as exc:  # JSON contract > the type
                    checks["aot_warm_ok"] = False
                    checks["aot_warm_error"] = str(exc)
                ok &= checks["aot_warm_ok"]

            # durable prefix CDN gate (ISSUE 20): the fleet-global
            # content-addressed prefix tier with its crash-safe disk
            # tail (disk_spill= → hostkv.DiskChainStore) is
            # contractually a CACHING change — restored chains are
            # crc-verified copies of the exported bytes, never
            # different tokens — so an armed 2-replica fleet must
            # BIT-match the single-engine baseline, and a RESTARTED
            # fleet (a brand-new fleet over the same spill dir: every
            # byte of RAM state gone, exactly a full-fleet crash) must
            # come back WARM from disk (restored chains > 0 converting
            # to store hits) and bit-match again, with zero frames
            # quarantined. Gates the disk tier on this host's real
            # filesystem/allocator before a preemptible serving pool
            # trusts a restart to be warm. TPU_PREFIX_DISK_SPILL
            # points the leg at a durable path (PVC / local-ssd —
            # wired by the gke-tpu smoketest Job); unset, a temp dir
            # proves the mechanism and is torn down.
            if checks.get("kv_spill_ok"):
                try:
                    import shutil
                    import tempfile

                    from ..models.fleet import make_fleet
                    from ..models.serving import make_serve_engine
                    from ..utils.traffic import shared_prefix_prompts

                    dcfg = BurnInConfig(
                        vocab=128, d_model=32, n_heads=4, d_ff=64,
                        n_layers=2, seq_len=16, batch=2,
                        dtype=jax.numpy.float32)
                    dparams = init_params(jax.random.PRNGKey(23), dcfg)
                    dpairs = shared_prefix_prompts(
                        6, seed=7, n_templates=2, template_len=8,
                        suffix_lo=1, suffix_hi=4, vocab=dcfg.vocab)
                    dprompts = [jax.numpy.asarray(p, jax.numpy.int32)
                                for _t, p in dpairs]
                    dbudgets = [3, 4, 2, 4, 3, 2]
                    dml = max(int(p.shape[-1]) + n
                              for p, n in zip(dprompts, dbudgets))
                    dbase = make_serve_engine(dparams, dcfg,
                                              max_len=dml, kv_block=4,
                                              share_prefix=True)
                    d_outs = dbase(dprompts, dbudgets, slots=2)
                    spill_env = e.get("TPU_PREFIX_DISK_SPILL")
                    ddir = spill_env or tempfile.mkdtemp(
                        prefix="smoke_cdn_")
                    try:
                        def cdn_run():
                            fl = make_fleet(
                                dparams, dcfg, max_len=dml, replicas=2,
                                kv_block=4, share_prefix=True,
                                steal=False, disk_spill=ddir)
                            outs = fl(dprompts, dbudgets, slots=2)
                            m = all(
                                o is not None
                                and bool(jax.device_get(
                                    jax.numpy.array_equal(o, b)))
                                for o, b in zip(outs, d_outs))
                            return (m, fl.cdn_store.disk_restored,
                                    fl.last_stats["fleet"]["cdn"])
                        m1, _r1, cdn1 = cdn_run()       # seeds disk
                        # the restart: new fleet, same dir, cold RAM
                        m2, restored, cdn2 = cdn_run()
                        checks["prefix_cdn_ok"] = (
                            m1 and m2 and restored > 0
                            and cdn1["store"]["disk"]["stored_chains"]
                            > 0
                            and cdn2["store"]["fetch_blocks"] > 0
                            and cdn2["store"]["disk"]["quarantined"]
                            == 0
                            and not cdn2["store"]["disk"]["dead"])
                        checks["prefix_cdn_durable_dir"] = \
                            bool(spill_env)
                        checks["prefix_cdn_restored_chains"] = restored
                        checks["prefix_cdn_hit_blocks"] = \
                            cdn2["store"]["fetch_blocks"]
                    finally:
                        if spill_env is None:
                            shutil.rmtree(ddir, ignore_errors=True)
                except Exception as exc:  # JSON contract > the type
                    checks["prefix_cdn_ok"] = False
                    checks["prefix_cdn_error"] = str(exc)
                ok &= checks["prefix_cdn_ok"]

            # flash pipeline gate: the software-pipelined kernels
            # (ops/flash_attention.py, pipeline="on") are contractually a
            # SCHEDULING change — same sub-tile folds, same arithmetic —
            # so a few train steps of a tiny flash config must BIT-match
            # the unpipelined kernels at equal block sizes, on this
            # slice's real lowering. Gates the kernel rewrite on chip
            # before a long burn-in trusts it. Tiny, unsharded and
            # process-local on purpose (no collectives — every host
            # validates independently at any world size).
            if checks["burnin_ok"]:
                try:
                    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64,
                                n_layers=2, seq_len=32, batch=4,
                                dtype=jax.numpy.float32, attn="flash",
                                flash_block_q=16, flash_block_k=8)
                    runs = {}
                    for mode in ("on", "off"):
                        pcfg = BurnInConfig(**base, flash_pipeline=mode)
                        pparams = init_params(jax.random.PRNGKey(9), pcfg)
                        pstep = make_train_step(pcfg)
                        pbatch = synthetic_batch(jax.random.PRNGKey(10),
                                                 pcfg)
                        for _ in range(2):
                            pparams, ploss = pstep(pparams, pbatch)
                        runs[mode] = (pparams, ploss)
                    leaves_on = jax.tree.leaves(runs["on"])
                    leaves_off = jax.tree.leaves(runs["off"])
                    bit_match = all(
                        bool(jax.device_get(jax.numpy.array_equal(a, b)))
                        for a, b in zip(leaves_on, leaves_off))
                    checks["flash_pipeline_ok"] = bit_match
                except Exception as exc:  # JSON contract > the type
                    checks["flash_pipeline_ok"] = False
                    checks["flash_pipeline_error"] = str(exc)
                ok &= checks["flash_pipeline_ok"]
            if ckpt is not None and ok:
                try:
                    checks["burnin_checkpoint_cleared"] = ckpt.clear()
                except Exception as exc:
                    checks["burnin_checkpoint_ok"] = False
                    checks["checkpoint_error"] = f"clear: {exc}"
                    ok = False
        finally:
            if ckpt is not None:
                ckpt.close()

    if level == "full" and ok:
        ok &= _run_full_level(checks, n_dev)

    return SmokeResult(bool(ok), checks, time.perf_counter() - t0)


def _run_full_level(checks: dict[str, Any], n_dev: int) -> bool:
    """The ep/pp fabric legs: all-to-all, MoE steps, a pipeline step.

    Uses the real package components (``models/moe.py`` via the burn-in
    config, ``parallel/pipeline.py``) on purpose-built meshes, so the
    checks validate the exact programs a workload would run. A single
    chip has no fabric to prove — the legs are skipped with an explicit
    marker instead of passing vacuously.
    """
    import jax

    from ..models import (
        BurnInConfig,
        init_params,
        make_train_step,
        synthetic_batch,
    )
    from ..parallel import build_mesh, make_rules, plan_mesh
    from ..parallel.collectives import all_to_all_probe
    from ..parallel.mesh import MeshPlan
    from ..parallel.pipeline import (
        PipelineConfig,
        init_pipeline_params,
        make_pipeline_train_step,
        stack_sharding,
    )

    ok = True
    if n_dev < 2:
        checks["full_skipped"] = "ep/pp fabric needs >= 2 devices"
        return ok

    # --- expert axis: all-to-all probe + MoE train steps (JSON contract
    # over bare tracebacks, matching the burn-in checkpoint policy)
    try:
        # ep-suffixed keys: the generic probes loop already recorded an
        # all_to_all over its fallback axis — both measurements stay
        ep_mesh = build_mesh(plan_mesh(n_dev, ep=2, tp=1))
        pr = all_to_all_probe(ep_mesh, axis="ep", n_elems=1 << 14)
        checks["all_to_all_ep_ok"] = pr["ok"]
        checks["all_to_all_ep_gibps"] = round(
            pr["bytes"] / max(pr["seconds"], 1e-9) / (1 << 30), 3)
        ok &= pr["ok"]

        rules = make_rules(ep_mesh)
        data_shards = ep_mesh.shape["dp"]
        cfg = BurnInConfig(n_experts=2, d_ff=256,
                           batch=max(8, 2 * data_shards))
        params = init_params(jax.random.PRNGKey(2), cfg, rules)
        step = make_train_step(cfg, rules)
        batch = synthetic_batch(jax.random.PRNGKey(3), cfg, rules)
        losses = []
        for _ in range(3):
            params, loss = step(params, batch)
            losses.append(float(loss))
        checks["moe_first_loss"] = round(losses[0], 4)
        checks["moe_last_loss"] = round(losses[-1], 4)
        checks["moe_ok"] = losses[-1] < losses[0]
    except Exception as exc:  # noqa: BLE001 — the JSON contract > the type
        checks["moe_ok"] = False
        checks["moe_error"] = str(exc)
    ok &= checks["moe_ok"]

    # --- pipeline axis: a 2-stage GPipe train step (gradients flow
    # through the reverse stage ppermutes)
    try:
        pp_mesh = build_mesh(MeshPlan(("pp", "dp"), (2, n_dev // 2)),
                             devices=jax.devices()[: 2 * (n_dev // 2)])
        pcfg = PipelineConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                              n_layers=2, seq_len=16, microbatch=2,
                              n_microbatches=2)
        pparams = init_pipeline_params(jax.random.PRNGKey(4), pcfg)
        pparams = jax.tree.map(jax.device_put, pparams,
                               stack_sharding(pp_mesh, pparams))
        pstep = make_pipeline_train_step(pcfg, pp_mesh)
        dp = pp_mesh.shape["dp"]
        total = pcfg.n_microbatches * pcfg.microbatch * dp
        stream = jax.random.randint(jax.random.PRNGKey(5),
                                    (total, pcfg.seq_len + 1), 0, pcfg.vocab)
        pbatch = (stream[:, :-1], stream[:, 1:])
        plosses = []
        for _ in range(3):
            pparams, ploss = pstep(pparams, pbatch)
            plosses.append(float(ploss))
        checks["pipeline_first_loss"] = round(plosses[0], 4)
        checks["pipeline_last_loss"] = round(plosses[-1], 4)
        checks["pipeline_ok"] = plosses[-1] < plosses[0]
    except Exception as exc:  # noqa: BLE001
        checks["pipeline_ok"] = False
        checks["pipeline_error"] = str(exc)
    ok &= checks["pipeline_ok"]

    # --- serving engine: slot-based continuous batching on the mesh —
    # the runtime the "serve"-named slice pools exist to run
    # (models/serving.py) proves out on the same fresh slice the train
    # legs validated. First tokens are compared against a BATCH-1
    # training forward per request — the same [1, plen] matmul shapes
    # the engine's row prefill runs, so the comparison carries only
    # decode_ok's residual near-tie risk, not a cross-batch-shape
    # tiling difference — and only FIRST tokens are compared (one
    # near-tie chance per request; later tokens would compound it).
    # The schedule runs 2x more requests than slots so slot recycling
    # actually happens. No eos is passed: the loop then never syncs
    # per step, which keeps it multi-controller-safe.
    try:
        from ..models import forward, make_serve_engine

        s_mesh = build_mesh(plan_mesh(n_dev))
        s_rules = make_rules(s_mesh)
        data_shards = s_mesh.shape["dp"]
        scfg = BurnInConfig(batch=max(2, data_shards))
        sparams = init_params(jax.random.PRNGKey(6), scfg, s_rules)
        slots = data_shards
        n_req, plen, n_new = 2 * slots, 8, 4
        prompts_mat = jax.random.randint(
            jax.random.PRNGKey(7), (n_req, plen), 0, scfg.vocab)
        engine = make_serve_engine(sparams, scfg, max_len=plen + n_new)
        outs = engine([prompts_mat[i] for i in range(n_req)], n_new,
                      slots=slots, rules=s_rules)
        ref_first = jax.numpy.stack([
            jax.numpy.argmax(
                forward(sparams, prompts_mat[i:i + 1], scfg)[0, -1],
                axis=-1)
            for i in range(n_req)])
        firsts = jax.numpy.stack([o[0] for o in outs])
        match = jax.numpy.all(firsts == ref_first)
        checks["serving_requests"] = n_req
        checks["serving_slots"] = slots
        checks["serving_ok"] = (
            all(o.shape == (n_new,) for o in outs)
            and bool(jax.device_get(match)))
        checks["serving_kv_utilisation"] = \
            engine.last_stats["kv"]["utilisation"]
    except Exception as exc:  # noqa: BLE001
        checks["serving_ok"] = False
        checks["serving_error"] = str(exc)
    ok &= checks["serving_ok"]
    return ok
