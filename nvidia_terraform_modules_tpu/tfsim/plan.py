# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Plan simulation: evaluate a module against tfvars, offline.

Produces the set of resource instances a ``terraform plan`` would create —
with provider-computed attributes rendered as ``<computed>`` — plus the
dependency DAG (cycle-checked, topologically ordered) and evaluated outputs.
Local-path child modules (``source = "../../"``, the reference's
examples/cnpack idiom — ``/root/reference/gke/examples/cnpack/main.tf:7``) are
simulated recursively; registry modules become fully-computed stubs.
"""

from __future__ import annotations

import dataclasses
import math
import os
import re
from typing import Any, Optional

from . import ast as A
from .eval import COMPUTED, EvalError, Scope, evaluate, is_computed
from .module import Module, Resource, load_module
from .parser import parse_hcl


class PlanError(ValueError):
    pass


class CycleError(PlanError):
    """A dependency cycle in the resource graph. ``cycle`` is the full
    node path (first element repeated last), so renderers — ``tfsim
    graph -cycles`` — can draw the loop instead of just naming it."""

    def __init__(self, cycle: list[str]):
        super().__init__("dependency cycle: " + " → ".join(cycle))
        self.cycle = cycle


class ResourceAttrs(dict):
    """Attribute map of a planned resource: unset keys are computed-at-apply."""

    def __missing__(self, key):
        return COMPUTED


@dataclasses.dataclass
class PlannedInstance:
    address: str            # e.g. google_container_cluster.cluster[0]
    attrs: ResourceAttrs


@dataclasses.dataclass
class Plan:
    module_path: str
    instances: dict[str, PlannedInstance]        # address → instance
    outputs: dict[str, Any]
    edges: list[tuple[str, str]]                 # (from_address, to_address)
    order: list[str]                             # topological apply order
    child_plans: dict[str, "Plan"] = dataclasses.field(default_factory=dict)
    check_failures: list[str] = dataclasses.field(default_factory=list)
    sensitive_outputs: set[str] = dataclasses.field(default_factory=set)
    # effective variable values (tfvars merged over declaration defaults,
    # optional() object attributes filled) — what var.* resolved to
    variables: dict[str, Any] = dataclasses.field(default_factory=dict)

    def instance(self, address: str) -> PlannedInstance:
        return self.instances[address]

    def addresses_of_type(self, rtype: str) -> list[str]:
        return [a for a in self.instances if a.split(".")[0] == rtype or
                (a.startswith("data.") and a.split(".")[1] == rtype)]


def load_tfvars(path: str) -> dict[str, Any]:
    """Parse a ``terraform.tfvars`` file (attributes of literals only)."""
    with open(path) as fh:
        body = parse_hcl(fh.read(), filename=path)
    scope = Scope()
    out = {}
    for attr in body.attributes:
        out[attr.name] = evaluate(attr.expr, scope)
    return out


# --------------------------------------------------------------------------
# reference extraction (for dependency edges)
# --------------------------------------------------------------------------

def _collect_addresses(node, resource_types: set[str],
                       locals_refs: dict[str, set[str]] | None = None) -> set[str]:
    """All resource/data/module addresses referenced from an AST subtree.

    ``locals_refs`` maps local name → addresses that local (transitively)
    references; a ``local.X`` reference pulls them in, so a resource that
    consumes a local depends on whatever the local reads.
    """
    out: set[str] = set()
    for t, bound in A.scoped_traversals(node):
        if t.root in bound:
            continue
        if t.root == "data" and len(t.ops) >= 2 and \
                t.ops[0][0] == "attr" and t.ops[1][0] == "attr":
            out.add(f"data.{t.ops[0][1]}.{t.ops[1][1]}")
        elif t.root == "module" and t.ops and t.ops[0][0] == "attr":
            out.add(f"module.{t.ops[0][1]}")
        elif t.root in resource_types and t.ops and t.ops[0][0] == "attr":
            out.add(f"{t.root}.{t.ops[0][1]}")
        elif t.root == "local" and locals_refs is not None and t.ops and \
                t.ops[0][0] == "attr":
            out |= locals_refs.get(t.ops[0][1], set())
    return out


class LazyLocals:
    """Terraform-faithful locals: evaluated on first reference, not up-front.

    A local may read resource attributes; eager evaluation would freeze it to
    ``<computed>`` before the resource is planned. Lazy evaluation (plus
    dependency expansion via ``locals_refs``) means a local referenced from a
    resource body sees every resource the plan order guarantees to exist.
    """

    def __init__(self, exprs: dict[str, A.Expr], scope: "Scope"):
        self._exprs = dict(exprs)
        self._scope = scope
        self._cache: dict[str, Any] = {}
        self._evaluating: set[str] = set()

    def __contains__(self, name: str) -> bool:
        return name in self._exprs or name in self._cache

    def __getitem__(self, name: str) -> Any:
        if name in self._cache:
            return self._cache[name]
        if name not in self._exprs:
            raise KeyError(name)
        if name in self._evaluating:
            raise EvalError(f"dependency cycle through local.{name}")
        self._evaluating.add(name)
        try:
            value = evaluate(self._exprs[name], self._scope)
        finally:
            self._evaluating.discard(name)
        self._cache[name] = value
        return value

    def __setitem__(self, name: str, value: Any) -> None:
        self._cache[name] = value

    def keys(self):
        return self._exprs.keys()



def module_locals_refs(module: Module, resource_types: set[str]) -> dict[str, set[str]]:
    """local name → resource/data/module addresses it (transitively) reads."""
    locals_refs: dict[str, set[str]] = {
        name: _collect_addresses(expr, resource_types)
        for name, expr in module.locals.items()
    }
    local_deps = {
        name: {
            t.ops[0][1]
            for t, bound in A.scoped_traversals(expr)
            if t.root == "local" and t.root not in bound and t.ops and
            t.ops[0][0] == "attr"
        }
        for name, expr in module.locals.items()
    }
    for _ in range(len(locals_refs)):
        changed = False
        for name, dep_names in local_deps.items():
            for d in dep_names:
                extra = locals_refs.get(d, set()) - locals_refs[name]
                if extra:
                    locals_refs[name] |= extra
                    changed = True
        if not changed:
            break
    return locals_refs


# --------------------------------------------------------------------------
# body evaluation
# --------------------------------------------------------------------------

_META_ATTRS = {"count", "for_each", "depends_on", "provider"}
_META_BLOCKS = {"lifecycle"}


def _eval_body(body: A.Body, scope: Scope, top_level: bool = False) -> ResourceAttrs:
    out = ResourceAttrs()
    for attr in body.attributes:
        # count/for_each/etc are resource meta-arguments only at the top level;
        # a nested block may legitimately have an attribute named "count"
        # (e.g. guest_accelerator { count = 2 })
        if top_level and attr.name in _META_ATTRS:
            continue
        value = evaluate(attr.expr, scope)
        if value is None:
            # terraform semantics: assigning null to an argument is the
            # same as omitting it — the conditional-omission idiom
            # (`x = cond ? v : null`) must not leave a null in the plan
            continue
        out[attr.name] = value
    for blk in body.blocks:
        if top_level and blk.type in _META_BLOCKS:
            continue
        if blk.type == "dynamic" and blk.labels:
            name = blk.labels[0]
            iterator = name
            ia = blk.body.attr("iterator")
            if ia is not None and isinstance(ia.expr, A.Traversal):
                iterator = ia.expr.root
            fe_attr = blk.body.attr("for_each")
            if fe_attr is None:
                raise PlanError(f"dynamic {name!r} block without for_each")
            coll = evaluate(fe_attr.expr, scope)
            if coll is COMPUTED:
                out.setdefault(name, COMPUTED)
                continue
            items = (
                list(coll.items()) if isinstance(coll, dict)
                else list(enumerate(coll))
            )
            content_blocks = blk.body.blocks_of("content")
            for k, v in items:
                sub = scope.child_bindings(**{iterator: {"key": k, "value": v}})
                for c in content_blocks:
                    out.setdefault(name, []).append(_eval_body(c.body, sub))
        else:
            out.setdefault(blk.type, []).append(_eval_body(blk.body, scope))
    return out


# --------------------------------------------------------------------------
# the simulator
# --------------------------------------------------------------------------

def simulate_plan(
    module: Module | str,
    tfvars: dict[str, Any] | None = None,
    *,
    workspace: str = "default",
    _depth: int = 0,
) -> Plan:
    if isinstance(module, str):
        module = load_module(module)
    if _depth > 4:
        raise PlanError("module recursion too deep")
    tfvars = dict(tfvars or {})

    # 1. variables ------------------------------------------------------
    variables: dict[str, Any] = {}
    base_scope = Scope()
    for name, var in module.variables.items():
        if name in tfvars:
            variables[name] = tfvars.pop(name)
        elif var.default is not None:
            variables[name] = evaluate(var.default, base_scope)
        else:
            raise PlanError(f"required variable {name!r} not set")
        variables[name] = _convert_value(
            variables[name], var.type_expr, base_scope, f"var.{name}")
    if tfvars:
        raise PlanError(f"unknown tfvars: {sorted(tfvars)}")

    scope = Scope(variables=variables, path_module=module.path,
                  workspace=workspace)

    # variable validation blocks (condition + error_message)
    for name, var in module.variables.items():
        for vblock in var.validations:
            cond_attr = vblock.body.attr("condition")
            if cond_attr is None:
                continue
            try:
                ok_v = evaluate(cond_attr.expr, scope)
            except EvalError:
                continue
            if ok_v is COMPUTED or ok_v:
                continue
            msg_attr = vblock.body.attr("error_message")
            msg = ""
            try:
                if msg_attr is not None:
                    msg = evaluate(msg_attr.expr, scope)
            except EvalError:
                pass
            raise PlanError(f"variable {name!r} validation failed: {msg}")

    # 2. locals: lazy, Terraform-style (a local may read resources planned
    #    later; evaluation happens at first reference, in plan order)
    scope.locals = LazyLocals(module.locals, scope)

    # 3. dependency graph over resources + data + module calls ----------
    resource_types = {r.type for r in module.resources.values()}
    nodes: dict[str, Any] = {}
    for addr, r in {**module.data_sources, **module.resources}.items():
        nodes[addr] = r
    for name, mc in module.module_calls.items():
        nodes[f"module.{name}"] = mc

    # per-local address refs, transitively closed through other locals
    locals_refs = module_locals_refs(module, resource_types)

    deps: dict[str, set[str]] = {}
    for addr, obj in nodes.items():
        body = obj.body
        refs = _collect_addresses(body, resource_types, locals_refs)
        deps[addr] = {r for r in refs if r in nodes and r != addr}

    order = _toposort(deps)

    # 4. walk in order, planning each node ------------------------------
    instances: dict[str, PlannedInstance] = {}
    child_plans: dict[str, Plan] = {}
    for addr in order:
        obj = nodes[addr]
        if addr.startswith("module."):
            _plan_module_call(addr, obj, module, scope, instances, _depth,
                              child_plans)
        else:
            _plan_resource(addr, obj, scope, instances)

    # 5. outputs --------------------------------------------------------
    outputs: dict[str, Any] = {}
    for name, out in module.outputs.items():
        if out.expr is None:
            outputs[name] = COMPUTED
            continue
        try:
            outputs[name] = evaluate(out.expr, scope)
        except EvalError as ex:
            raise PlanError(f"output {name!r}: {ex}")

    # 6. check blocks: postconditions, terraform-style (failures warn, the
    #    plan itself still succeeds) -------------------------------------
    check_failures: list[str] = []
    for blk in module.checks:
        label = blk.labels[0] if blk.labels else "<unnamed>"
        for ab in blk.body.blocks_of("assert"):
            cond_attr = ab.body.attr("condition")
            if cond_attr is None:
                continue
            try:
                ok_v = evaluate(cond_attr.expr, scope)
            except EvalError:
                continue
            if ok_v is COMPUTED or ok_v:
                continue
            msg = ""
            msg_attr = ab.body.attr("error_message")
            try:
                if msg_attr is not None:
                    msg = evaluate(msg_attr.expr, scope)
            except EvalError:
                pass
            check_failures.append(f"check {label!r}: {msg}")

    edges = [(a, d) for a, ds in deps.items() for d in ds]
    return Plan(
        module_path=module.path, instances=instances, outputs=outputs,
        edges=edges, order=order, child_plans=child_plans,
        check_failures=check_failures,
        sensitive_outputs={n for n, o in module.outputs.items()
                           if o.sensitive},
        variables=variables,
    )


def _convert_value(value: Any, type_expr, scope: Scope, path: str) -> Any:
    """ONE pass over the declared type: fill ``optional()`` defaults AND
    coerce/check, terraform's convert semantics for the tfsim subset.

    - primitives inter-convert ("5" → 5 for number, bools/strings both
      ways); number rejects inf/nan/underscore spellings like terraform;
    - collections (list/set/map/tuple) convert element-wise;
    - objects check every declared attribute: present values convert,
      missing/null optional attributes take their declared default
      (terraform 1.3+ semantics), missing required attributes and
      UNDECLARED attributes fail the plan with the value's path;
    - ``any`` / unknown constructors / computed values pass through.

    One walker on purpose: a defaults pass and a separate coercion pass
    over the same grammar drift apart (the type system's single source of
    truth lives here).
    """
    if type_expr is None or value is COMPUTED:
        return value
    # ---- primitive names ------------------------------------------------
    if isinstance(type_expr, A.Traversal) and not type_expr.ops:
        if value is None:
            return None
        t = type_expr.root
        if t == "string":
            if isinstance(value, str):
                return value
            if isinstance(value, bool):
                return "true" if value else "false"
            if isinstance(value, (int, float)):
                if isinstance(value, float) and not math.isfinite(value):
                    raise PlanError(
                        f"{path}: cannot convert {value!r} to string")
                return str(int(value)) if isinstance(value, float) and \
                    value == int(value) else str(value)
            raise PlanError(
                f"{path}: cannot convert {type(value).__name__} to string")
        if t == "number":
            if isinstance(value, bool):
                raise PlanError(f"{path}: cannot convert bool to number")
            if isinstance(value, (int, float)):
                # terraform numbers are finite decimals; json.loads lets
                # Infinity/NaN through -var, reject them here
                if isinstance(value, float) and not math.isfinite(value):
                    raise PlanError(
                        f"{path}: cannot convert {value!r} to number")
                return value
            if isinstance(value, str):
                # terraform's number syntax only — no inf/nan/underscores
                if re.fullmatch(r"-?\d+", value.strip()):
                    return int(value)
                if re.fullmatch(r"-?\d*\.?\d+([eE][+-]?\d+)?",
                                value.strip()):
                    return float(value)
            raise PlanError(f"{path}: cannot convert {value!r} to number")
        if t == "bool":
            if isinstance(value, bool):
                return value
            if isinstance(value, str) and value in ("true", "false"):
                return value == "true"
            raise PlanError(
                f"{path}: cannot convert {type(value).__name__} to bool")
        return value                         # any / unknown names
    if not isinstance(type_expr, A.Call):
        return value
    name, targs = type_expr.name, type_expr.args
    if name == "optional" and targs:
        if value is None:
            default = (evaluate(targs[1], scope) if len(targs) > 1 else None)
            return _convert_value(default, targs[0], scope, path)
        return _convert_value(value, targs[0], scope, path)
    if value is None:
        return None
    if name in ("list", "set") and targs:
        if not isinstance(value, (list, tuple)):
            raise PlanError(
                f"{path}: {name} required, got {type(value).__name__}")
        return [_convert_value(v, targs[0], scope, f"{path}[{i}]")
                for i, v in enumerate(value)]
    if name == "map" and targs:
        if not isinstance(value, dict):
            raise PlanError(
                f"{path}: map required, got {type(value).__name__}")
        return {k: _convert_value(v, targs[0], scope, f"{path}[{k!r}]")
                for k, v in value.items()}
    if name == "tuple" and targs and isinstance(targs[0], A.TupleExpr):
        items = targs[0].items
        if not isinstance(value, (list, tuple)) or len(value) != len(items):
            raise PlanError(f"{path}: tuple of {len(items)} required")
        return [_convert_value(v, t, scope, f"{path}[{i}]")
                for i, (v, t) in enumerate(zip(value, items))]
    if name == "object" and targs and isinstance(targs[0], A.ObjectExpr):
        if not isinstance(value, dict):
            raise PlanError(
                f"{path}: object required, got {type(value).__name__}")
        spec: dict[str, Any] = {}
        for it in targs[0].items:
            if isinstance(it.key, A.Literal):
                spec[str(it.key.value)] = it.value
        extra = sorted(set(value) - set(spec))
        if extra:
            raise PlanError(
                f"{path}: unexpected object attribute(s) "
                f"{', '.join(extra)} (declared: {', '.join(sorted(spec))})")
        out: dict[str, Any] = {}
        for key, t in spec.items():
            is_optional = isinstance(t, A.Call) and t.name == "optional"
            if value.get(key) is not None:
                out[key] = _convert_value(value[key], t, scope,
                                          f"{path}.{key}")
            elif is_optional:
                # terraform 1.3+: missing AND explicit null both take the
                # optional() default
                out[key] = _convert_value(None, t, scope, f"{path}.{key}")
            elif key in value:
                out[key] = None  # explicit null on a non-optional attribute
            else:
                raise PlanError(
                    f"{path}: object value missing required attribute "
                    f"{key!r}")
        return out
    return value


def _plan_resource(addr: str, r: Resource, scope: Scope,
                   instances: dict[str, PlannedInstance]) -> None:
    count_attr = r.body.attr("count")
    foreach_attr = r.body.attr("for_each")

    def register(value: Any):
        table = scope.data if r.mode == "data" else scope.resources
        table.setdefault(r.type, {})[r.name] = value

    if count_attr is not None:
        n = evaluate(count_attr.expr, scope)
        if n is COMPUTED:
            raise PlanError(f"{addr}: count is computed at plan time")
        n = int(n)
        vals = []
        for i in range(n):
            sub = Scope(scope.variables, scope.locals, scope.resources,
                        scope.data, scope.modules, None, i, scope.path_module,
                        scope.workspace)
            sub.bindings = dict(scope.bindings)
            attrs = _eval_body(r.body, sub, top_level=True)
            attrs.setdefault("id", COMPUTED)
            inst = PlannedInstance(f"{addr}[{i}]", attrs)
            instances[inst.address] = inst
            vals.append(attrs)
        register(vals)
    elif foreach_attr is not None:
        coll = evaluate(foreach_attr.expr, scope)
        if coll is COMPUTED:
            raise PlanError(f"{addr}: for_each is computed at plan time")
        items = (
            list(coll.items()) if isinstance(coll, dict)
            else [(k, k) for k in coll]
        )
        vals = {}
        for k, v in items:
            sub = Scope(scope.variables, scope.locals, scope.resources,
                        scope.data, scope.modules,
                        {"key": k, "value": v}, None, scope.path_module,
                        scope.workspace)
            sub.bindings = dict(scope.bindings)
            attrs = _eval_body(r.body, sub, top_level=True)
            attrs.setdefault("id", COMPUTED)
            inst = PlannedInstance(f'{addr}["{k}"]', attrs)
            instances[inst.address] = inst
            vals[k] = attrs
        register(vals)
    else:
        attrs = _eval_body(r.body, scope, top_level=True)
        attrs.setdefault("id", COMPUTED)
        inst = PlannedInstance(addr, attrs)
        instances[inst.address] = inst
        register(attrs)


class _ComputedModule(dict):
    def __missing__(self, key):
        return COMPUTED


def _plan_module_call(addr: str, mc, parent: Module, scope: Scope,
                      instances: dict[str, PlannedInstance],
                      depth: int,
                      child_plans: dict[str, "Plan"] | None = None) -> None:
    src_attr = mc.body.attr("source")
    src = None
    if src_attr is not None and isinstance(src_attr.expr, A.Literal):
        src = src_attr.expr.value

    # expansion: count = 0/N and for_each are honoured (a conditional module
    # with count = 0 must plan nothing)
    count_attr = mc.body.attr("count")
    foreach_attr = mc.body.attr("for_each")
    if count_attr is not None and foreach_attr is not None:
        raise PlanError(f"{addr}: both count and for_each set")
    expansions: list[tuple[str, Scope]]  # (address suffix, scope for args)
    if count_attr is not None:
        n = evaluate(count_attr.expr, scope)
        if n is COMPUTED:
            raise PlanError(f"{addr}: count is computed at plan time")
        expansions = []
        for i in range(int(n)):
            sub = Scope(scope.variables, scope.locals, scope.resources,
                        scope.data, scope.modules, None, i, scope.path_module,
                        scope.workspace)
            sub.bindings = dict(scope.bindings)
            expansions.append((f"[{i}]", sub))
    elif foreach_attr is not None:
        coll = evaluate(foreach_attr.expr, scope)
        if coll is COMPUTED:
            raise PlanError(f"{addr}: for_each is computed at plan time")
        items = (list(coll.items()) if isinstance(coll, dict)
                 else [(k, k) for k in coll])
        expansions = []
        for k, v in items:
            sub = Scope(scope.variables, scope.locals, scope.resources,
                        scope.data, scope.modules, {"key": k, "value": v},
                        None, scope.path_module, scope.workspace)
            sub.bindings = dict(scope.bindings)
            expansions.append((f'["{k}"]', sub))
    else:
        expansions = [("", scope)]

    def plan_one(suffix: str, sub_scope: Scope):
        args = {}
        for attr in mc.body.attributes:
            if attr.name in ("source", "version", "providers", "depends_on",
                             "count", "for_each"):
                continue
            args[attr.name] = evaluate(attr.expr, sub_scope)
        if src and (src.startswith("./") or src.startswith("../")):
            child_path = os.path.normpath(os.path.join(parent.path, src))
            child_plan = simulate_plan(child_path, args, _depth=depth + 1,
                                       workspace=sub_scope.workspace)
            if child_plans is not None:
                child_plans[f"{addr}{suffix}"] = child_plan
            for iaddr, inst in child_plan.instances.items():
                instances[f"{addr}{suffix}.{iaddr}"] = inst
            return dict(child_plan.outputs)
        instances[f"{addr}{suffix}"] = PlannedInstance(
            f"{addr}{suffix}", ResourceAttrs(args))
        return _ComputedModule()

    if count_attr is not None:
        scope.modules[mc.name] = [plan_one(s, sc) for s, sc in expansions]
    elif foreach_attr is not None:
        scope.modules[mc.name] = {
            s[2:-2]: plan_one(s, sc) for s, sc in expansions}
    else:
        scope.modules[mc.name] = plan_one("", scope)


def _toposort(deps: dict[str, set[str]]) -> list[str]:
    order: list[str] = []
    state: dict[str, int] = {}  # 0 new, 1 visiting, 2 done

    def visit(n: str, chain: list[str]):
        st = state.get(n, 0)
        if st == 2:
            return
        if st == 1:
            raise CycleError(chain[chain.index(n):] + [n])
        state[n] = 1
        for d in sorted(deps.get(n, ())):
            visit(d, chain + [n])
        state[n] = 2
        order.append(n)

    for n in sorted(deps):
        visit(n, [])
    return order


def instance_node(iaddr: str) -> str:
    """Instance address → its graph node (``module.x.res[0]`` → ``module.x``,
    ``type.name["k"]`` → ``type.name``)."""
    if iaddr.startswith("module."):
        return ".".join(iaddr.split(".")[:2]).split("[")[0]
    return iaddr.split("[")[0]


def _node_closure(plan: Plan) -> dict[str, set[str]]:
    """Node → every node it transitively depends on, over ``plan.edges``."""
    deps: dict[str, set[str]] = {}
    for frm, to in plan.edges:
        deps.setdefault(frm, set()).add(to)
    closure: dict[str, set[str]] = {}

    def visit(n: str) -> set[str]:
        got = closure.get(n)
        if got is not None:
            return got
        closure[n] = set()      # cycle guard; plan graphs are acyclic
        out: set[str] = set()
        for dep in deps.get(n, ()):
            out.add(dep)
            out |= visit(dep)
        closure[n] = out
        return out

    for n in plan.order:
        visit(n)
    return closure


def instance_dependencies(plan: Plan, addrs) -> dict[str, set[str]]:
    """Instance-level dependency edges among ``addrs``.

    ``out[a]`` is the subset of ``addrs`` that ``a`` depends on. Edges
    come from the *transitive* node closure, so an intermediate node
    with no operation of its own (a no-op, a data source, a node absent
    from ``addrs``) still gates its endpoints — the property the
    graph-parallel apply scheduler needs ("no operation starts before
    everything it depends on completed"). Instances that live inside
    the same child-module call are resolved against that child plan's
    own edges (node-level ``plan.edges`` collapses a whole module call
    to one node and would read its internals as mutually independent);
    instances of *different* expansions of one module call stay
    independent, matching terraform's per-instance subgraphs.

    Addresses whose node the plan does not know (present only in
    state) get no edges: the simulated statefile records no dependency
    information, so they schedule freely.
    """
    addrs = list(addrs)
    out: dict[str, set[str]] = {a: set() for a in addrs}
    closure = _node_closure(plan)
    by_node: dict[str, list[str]] = {}
    for a in addrs:
        by_node.setdefault(instance_node(a), []).append(a)
    for n1, instances in by_node.items():
        cl = closure.get(n1)
        if not cl:
            continue
        for n2, dep_instances in by_node.items():
            if n2 == n1 or n2 not in cl:
                continue
            for a in instances:
                out[a].update(dep_instances)
    # module-internal edges, per child-module instance
    for key, child in plan.child_plans.items():
        prefix = key + "."
        inner = {a[len(prefix):]: a for a in addrs if a.startswith(prefix)}
        if len(inner) < 2:
            continue
        for iaddr, ideps in instance_dependencies(child, inner).items():
            out[inner[iaddr]].update(inner[dep] for dep in ideps)
    return out


def instance_apply_order(plan: Plan, addrs, deps=None) -> list[str]:
    """Deterministic apply order for instance addresses.

    A topological linearisation of :func:`instance_dependencies`,
    tie-broken by the plan's node rank and then the address — so for a
    flat module it reproduces the historical (rank, address) sort
    exactly, while module-internal edges are honoured where a plain
    sort would violate them. State-only addresses (present in state,
    absent from the plan graph) take a **stable rank**: strictly after
    every planned node, ordered by bare address — delete ordering can
    never drift between runs however the plan around them changes. The
    stepwise fault-injecting apply performs operations in exactly this
    sequence at ``-parallelism 1``, so a given ``-fault-seed`` always
    lands its faults on the same operations.

    ``deps`` (a precomputed ``instance_dependencies(plan, addrs)``) is
    accepted so a caller that needs the edge map anyway — the apply
    scheduler — doesn't pay for the closure twice."""
    import heapq

    addrs = list(addrs)
    rank = {n: i for i, n in enumerate(plan.order)}

    def key(a: str):
        node = instance_node(a)
        # state-only addresses sort in their own band (1, addr): the
        # rank is a function of the address alone, nothing else
        return (0, rank[node], a) if node in rank else (1, a)

    if deps is None:
        deps = instance_dependencies(plan, addrs)
    waiting = {a: set(ds) for a, ds in deps.items()}
    dependents: dict[str, list[str]] = {}
    for a, ds in deps.items():
        for dep in ds:
            dependents.setdefault(dep, []).append(a)
    heap = [key(a) for a in addrs if not waiting[a]]
    heapq.heapify(heap)
    out: list[str] = []
    while heap:
        a = heapq.heappop(heap)[-1]
        out.append(a)
        for dep in dependents.get(a, ()):
            pending = waiting[dep]
            pending.discard(a)
            if not pending:
                heapq.heappush(heap, key(dep))
    if len(out) != len(addrs):     # unreachable on acyclic plans —
        raise PlanError(           # but never silently drop operations
            "internal: instance dependency cycle among " +
            ", ".join(sorted(set(addrs) - set(out))))
    return out


def select_targets(plan: Plan, targets: list[str],
                   instances=None) -> set[str]:
    """Instance addresses covered by ``-target`` flags, terraform-style.

    Each target names a node (``google_x.y``, ``module.m``) or a single
    instance (``google_x.y["k"]``); the selection is that target plus the
    transitive closure of everything it depends on. Dependencies are
    node-level (matching terraform: a depended-on resource is included
    whole), while a bracketed leaf target keeps only its own instance.
    ``instances`` widens the candidate universe beyond the plan's own
    (the diff passes planned ∪ prior so targeted deletes of
    removed-from-config instances select too). Raises :class:`PlanError`
    for a target matching nothing in the configuration.
    """
    universe = plan.instances if instances is None else instances

    kept: set[str] = set()
    for t in targets:
        selected = _select_one(plan, t, universe, "")
        if "[" in t and not any(_under(i, t) for i in universe):
            # a bracketed key that matches no live instance is a typo —
            # erroring beats silently applying only the dependency
            # closure. (An unbracketed target of a count=0/empty-for_each
            # resource is legal and simply selects nothing, matching
            # terraform; config-existence is checked in _select_one.)
            raise PlanError(
                f"target {t!r} matches no resource instance in the "
                f"configuration or state")
        kept |= selected
    return kept


def _under(iaddr: str, t: str) -> bool:
    """iaddr is the target itself, an instance of it, or inside it."""
    return iaddr == t or iaddr.startswith(t + "[") or \
        iaddr.startswith(t + ".")


def _select_one(plan: Plan, t: str, universe, prefix: str) -> set[str]:
    """Instances selected by ONE target, relative to ``plan``.

    ``t`` is the target path relative to this plan; ``prefix`` maps this
    plan's addresses back into the root universe (``"module.m."`` when
    recursing). Dependency closure runs over this plan's edges; a target
    that descends into a local child module recurses so in-module
    dependencies are honoured too.
    """
    deps: dict[str, set[str]] = {}
    for frm, to in plan.edges:
        deps.setdefault(frm, set()).add(to)

    node = instance_node(t)
    if node not in plan.order:
        # fully removed from config: terraform still plans a targeted
        # destroy for the state-only addresses (the universe carries
        # prior state when called from diff)
        prior_hits = {i for i in universe if _under(i, prefix + t)}
        if not prior_hits:
            raise PlanError(
                f"target {prefix + t!r} matches no resource in the "
                f"configuration or state")
        return prior_hits

    closure: set[str] = set()
    work = [node]
    while work:
        n = work.pop()
        if n in closure:
            continue
        closure.add(n)
        work.extend(deps.get(n, ()))

    kept: set[str] = set()
    for iaddr in universe:
        rel = iaddr[len(prefix):] if iaddr.startswith(prefix) else None
        if rel is None:
            continue
        inode = instance_node(rel)
        if inode not in closure:
            continue
        if inode == node and t != node:
            # target is more specific than its node: a bracketed instance
            # keeps only itself; a module-inner path recurses below
            continue
        kept.add(iaddr)

    if t != node and node.startswith("module."):
        # descend: module.m.google_x.y selects that resource plus its
        # dependencies WITHIN the child module (child edges), not the
        # module's unrelated resources. On an expanded module
        # (count/for_each), module.m[0].res targets one instance and the
        # index-less module.m.res targets the resource in EVERY instance
        # (terraform's accepted all-instances form).
        matched = False
        for key, child in plan.child_plans.items():
            if instance_node(key) != node:
                continue
            if t.startswith(key + "."):
                inner = t[len(key) + 1:]
            elif key != node and t.startswith(node + ".") and \
                    not t.startswith(node + "["):
                inner = t[len(node) + 1:]
            else:
                continue
            kept |= _select_one(child, inner, universe, prefix + key + ".")
            matched = True
        if not matched:
            # module.m[0] as a whole, or a registry-stub module with no
            # child plan: the whole subtree
            kept |= {i for i in universe if _under(i, prefix + t)}
    elif t != node:
        # bracketed resource instance (res["k"]): just that subtree
        kept |= {i for i in universe if _under(i, prefix + t)}
    return kept


_ADDR_RE = re.compile(
    r"^(?P<type>[\w-]+)\.(?P<name>[\w-]+)"
    r"(?:\[(?:\"(?P<key>[^\"]*)\"|(?P<idx>\d+))\])?$")


def plan_eval_scope(plan: Plan, variables: dict[str, Any],
                    run_outputs: dict[str, dict[str, Any]] | None = None,
                    ) -> Scope:
    """Name resolution over a completed plan (asserts, console).

    Rebuilds the resource/data tables from the planned instances (count →
    list, for_each → dict, plain → attrs — the same shapes the planner
    registers while evaluating the module), wires child-module outputs under
    ``module.*``, the module's own outputs under ``output.*``, and earlier
    runs under ``run.*``.
    """
    resources: dict[str, dict[str, Any]] = {}
    data: dict[str, dict[str, Any]] = {}

    # seed every planned node so a count=0 / empty-for_each resource still
    # resolves (terraform: an empty tuple, so `length(x) == 0` asserts work)
    for addr in plan.order:
        if addr.startswith("module."):
            continue
        is_data = addr.startswith("data.")
        m = _ADDR_RE.match(addr[5:] if is_data else addr)
        if m is not None:
            (data if is_data else resources).setdefault(
                m.group("type"), {}).setdefault(m.group("name"), [])

    for addr, inst in plan.instances.items():
        if addr.startswith("module."):
            continue
        is_data = addr.startswith("data.")
        m = _ADDR_RE.match(addr[5:] if is_data else addr)
        if m is None:
            continue
        table = data if is_data else resources
        slot = table.setdefault(m.group("type"), {})
        if m.group("key") is not None:
            if not isinstance(slot.get(m.group("name")), dict):
                slot[m.group("name")] = {}     # replace the seeded []
            slot[m.group("name")][m.group("key")] = inst.attrs
        elif m.group("idx") is not None:
            lst = slot.setdefault(m.group("name"), [])
            lst.insert(int(m.group("idx")), inst.attrs)
        else:
            slot[m.group("name")] = inst.attrs

    modules: dict[str, Any] = {}
    for key, child in plan.child_plans.items():
        m = re.match(r'^module\.([\w-]+)(?:\[(?:"([^"]*)"|(\d+))\])?$', key)
        if m is None:
            continue
        name, fkey, idx = m.group(1), m.group(2), m.group(3)
        if fkey is not None:
            modules.setdefault(name, {})[fkey] = dict(child.outputs)
        elif idx is not None:
            modules.setdefault(name, []).insert(int(idx), dict(child.outputs))
        else:
            modules[name] = dict(child.outputs)

    scope = Scope(variables=dict(variables), resources=resources, data=data,
                  modules=modules)
    scope.bindings["output"] = dict(plan.outputs)
    scope.bindings["run"] = run_outputs or {}
    return scope



def to_dot(plan: Plan) -> str:
    """Render the dependency DAG as GraphViz DOT (``terraform graph``).

    Edges point from a node to what it depends on, matching terraform's
    drawing direction; nodes with no edges still appear so the graph is a
    complete inventory of the plan.
    """
    lines = ["digraph {", "  rankdir = \"RL\";"]
    for addr in plan.order:
        lines.append(f'  "{addr}";')
    for frm, to in sorted(plan.edges):
        lines.append(f'  "{frm}" -> "{to}";')
    lines.append("}")
    return "\n".join(lines) + "\n"


def cycle_to_dot(cycle: list[str]) -> str:
    """Render a dependency cycle (:class:`CycleError` payload) as a DOT
    subgraph highlight — ``tfsim graph -cycles``. Edges keep
    :func:`to_dot`'s direction (node → what it depends on); the whole
    loop is red so it pops out of any surrounding graph drawing."""
    lines = ["digraph {", "  rankdir = \"RL\";",
             "  subgraph cluster_cycle {",
             "    label = \"dependency cycle\";",
             "    color = \"red\";"]
    for addr in cycle[:-1]:
        lines.append(f'    "{addr}" [color = "red"];')
    for frm, to in zip(cycle, cycle[1:]):
        lines.append(f'    "{frm}" -> "{to}" [color = "red"];')
    lines += ["  }", "}"]
    return "\n".join(lines) + "\n"


def render(value: Any) -> Any:
    """Plan value → JSON-friendly structure (COMPUTED → "<computed>")."""
    if value is COMPUTED:
        return "<computed>"
    if isinstance(value, dict):
        return {k: render(v) for k, v in value.items()}
    if isinstance(value, list):
        return [render(v) for v in value]
    return value
