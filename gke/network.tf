# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Optional dedicated VPC + subnet (L1 in the survey layer map).
#
# Capability parity: reference creates holoscan-vpc / holoscan-subnet gated on
# vpc_enabled (/root/reference/gke/main.tf:7-24). Here the toggle and the
# bring-your-own names live in one object variable, and the derived
# network/subnetwork selection is a local so the cluster resource reads one
# expression instead of repeating the conditional.

locals {
  create_vpc      = var.network.create
  network_name    = local.create_vpc ? google_compute_network.vpc[0].name : var.network.existing_network
  subnetwork_name = local.create_vpc ? google_compute_subnetwork.cluster[0].name : var.network.existing_subnetwork
}

resource "google_compute_network" "vpc" {
  count = local.create_vpc ? 1 : 0

  name                    = "${var.cluster_name}-net"
  project                 = var.project_id
  auto_create_subnetworks = false
}

resource "google_compute_subnetwork" "cluster" {
  count = local.create_vpc ? 1 : 0

  name                     = "${var.cluster_name}-subnet"
  project                  = var.project_id
  region                   = var.region
  network                  = google_compute_network.vpc[0].id
  ip_cidr_range            = var.network.subnet_cidr
  private_ip_google_access = true
}
