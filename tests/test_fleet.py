# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Fleet router: placement is scheduling, never a different model.

The router's contract (models/fleet.py): whatever the placement —
one replica, N affinity-routed replicas, random placement, stolen
requests, disaggregated prefill/decode — every served request's tokens
equal ``greedy_decode`` run alone on that request, because each engine
keeps the serving engine's exactness contract and the router only
decides WHERE and WHEN. These tests force the interesting fleet
schedules: single-replica (the bare-engine bit-match), Zipf template
traffic (affinity earns hit fraction), deliberate imbalance (work
stealing), tight deadlines (deterministic shedding), and the
prefill→decode role split (block handoff between pools).
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    greedy_decode,
    init_params,
    make_fleet,
    make_serve_engine,
)
from nvidia_terraform_modules_tpu.utils.traffic import (
    poisson_trace,
    shared_prefix_prompts,
    slo_deadlines,
)

CFG = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
           seq_len=16, batch=2, dtype=jnp.float32)


@functools.lru_cache(maxsize=None)
def _setup():
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    keys = jax.random.split(jax.random.PRNGKey(1), 6)
    prompts = tuple(
        jax.random.randint(k, (4 + (i % 3) * 2,), 0, cfg.vocab)
        for i, k in enumerate(keys))
    return cfg, params, prompts


@functools.lru_cache(maxsize=None)
def _zipf_setup(n=10):
    """Shared-template Zipf workload — the traffic shape affinity
    routing exists for (template spans align to kv_block=4 blocks)."""
    cfg = BurnInConfig(**{**CFG, "seq_len": 32})
    params = init_params(jax.random.PRNGKey(2), cfg)
    pairs = shared_prefix_prompts(n, seed=0, n_templates=3,
                                  template_len=8, suffix_lo=1,
                                  suffix_hi=4, vocab=cfg.vocab)
    prompts = tuple(jnp.asarray(p, jnp.int32) for _t, p in pairs)
    max_len = max(int(p.shape[-1]) for p in prompts) + 5
    return cfg, params, prompts, max_len


def _solo(params, prompts, n_new, cfg, **kw):
    return [greedy_decode(params, p[None, :], n_new, cfg, **kw)[0]
            for p in prompts]


def _assert_all_equal(outs, want, label=""):
    for i, (g, w) in enumerate(zip(outs, want)):
        assert g is not None, f"{label} request {i} unserved"
        assert jnp.array_equal(g, w), f"{label} request {i} diverged"


def test_fleet_single_replica_bit_matches_bare_engine_tier1():
    """Router on, one replica: per-request outputs equal the bare
    engine's AND solo greedy — the router adds a queue and a thread,
    never different math."""
    cfg, params, prompts = _setup()
    bare = make_serve_engine(params, cfg, max_len=16, kv_block=4)
    want = bare(prompts, 6, slots=2)
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4)
    got = fleet(prompts, 6, slots=2)
    _assert_all_equal(got, want, "vs bare engine:")
    _assert_all_equal(got, _solo(params, prompts, 6, cfg), "vs solo:")
    st = fleet.last_stats["fleet"]
    assert st["served"] == len(prompts) and st["shed"] == 0
    assert fleet.last_stats["replica_stats"][0]["kv"]["in_use"] == 0


def test_fleet_affinity_routing_bit_matches_solo_and_earns_hits():
    """N replicas under affinity routing on the Zipf template trace:
    every request still equals its solo decode REGARDLESS of
    placement, same-template prompts land together (the per-replica
    prefix index actually fires), and affinity beats seeded-random
    placement on hit fraction — the acceptance bar."""
    cfg, params, prompts, max_len = _zipf_setup()
    want = _solo(params, prompts, 5, cfg)
    hit = {}
    for routing in ("affinity", "random"):
        fleet = make_fleet(params, cfg, max_len=max_len, replicas=2,
                           kv_block=4, share_prefix=True,
                           routing=routing, steal=False)
        got = fleet(prompts, 5, slots=2)
        _assert_all_equal(got, want, routing)
        hit[routing] = fleet.last_stats["fleet"]["affinity_hit_frac"]
    assert hit["affinity"] > 0
    # affinity routing must STRICTLY raise the prefix hit fraction
    # over random placement on the Zipf trace (ISSUE 12 acceptance)
    assert hit["affinity"] > hit["random"], hit


def test_fleet_disaggregated_bit_matches_colocated_and_solo():
    """The Podracer role split: prefill workers hand paged blocks to
    decode workers, and the outputs bit-match both the colocated fleet
    and solo greedy — the handoff moves bytes, never changes them."""
    cfg, params, prompts, max_len = _zipf_setup()
    colo = make_fleet(params, cfg, max_len=max_len, replicas=2,
                      kv_block=4, share_prefix=True, steal=False)
    want_colo = colo(prompts, 5, slots=2)
    dis = make_fleet(params, cfg, max_len=max_len, replicas=3,
                     kv_block=4, share_prefix=True, disaggregate=True,
                     prefill_workers=1, steal=False)
    got = dis(prompts, 5, slots=2)
    _assert_all_equal(got, want_colo, "vs colocated:")
    _assert_all_equal(got, _solo(params, prompts, 5, cfg), "vs solo:")
    st = dis.last_stats["fleet"]
    assert st["mode"] == "disaggregated" and st["prefill_workers"] == 1
    roles = {r["role"] for r in st["per_replica"]}
    assert roles == {"prefill", "decode"}
    pre = [r for r in st["per_replica"] if r["role"] == "prefill"]
    assert sum(r["requests"] for r in pre) == len(prompts)
    # the prefill side's prefix index shares templates across requests
    assert st["affinity_hit_frac"] > 0
    # decode pools drained (imported blocks freed at retirement)
    for rs in dis.last_stats["replica_stats"]:
        assert rs["kv"]["in_use"] == 0


def test_fleet_slo_shedding_is_deterministic_and_partial():
    """Deadline admission: the virtual-clock shed plan is a pure
    function of the trace (replays identically), sheds a STRICT subset
    (the backlogged tail blows deadlines, the head does not), returns
    None exactly at shed indexes, and serves everything else solo-
    exact with attainment billed."""
    cfg, params, prompts = _setup()
    n = len(prompts)
    arrivals = poisson_trace(500.0, n, seed=4)     # a burst: backlog
    budgets = [6] * n
    deadlines = slo_deadlines(budgets, seed=5, base_s=0.08,
                              per_token_s=0.01, jitter=0.2)
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.02)
    got = fleet(prompts, budgets, slots=2, arrivals=arrivals,
                deadlines=deadlines)
    st = fleet.last_stats["fleet"]
    # a 1-replica serial virtual clock at 0.02 s/token: ~0.12 s per
    # request against ~0.14 s deadlines — the queue head fits, the
    # tail cannot: a strict, non-empty, non-total shed set
    assert 0 < st["shed"] < n, st
    assert all(got[r] is None for r in st["shed_requests"])
    want = _solo(params, prompts, 6, cfg)
    for req in range(n):
        if req not in st["shed_requests"]:
            assert jnp.array_equal(got[req], want[req]), req
    assert st["deadline_attainment"] is not None
    assert st["served"] + st["shed"] == n
    # replay: identical shed set (determinism the bench gate relies on)
    fleet(prompts, budgets, slots=2, arrivals=arrivals,
          deadlines=deadlines)
    assert fleet.last_stats["fleet"]["shed_requests"] \
        == st["shed_requests"]


def test_fleet_work_stealing_rebalances_a_backed_up_queue():
    """All requests share one template → affinity sends every one to
    the same replica while the other idles: the monitor must steal at
    least one pending request across, and outputs stay solo-exact."""
    cfg, params, _ = _setup()
    tmpl = jax.random.randint(jax.random.PRNGKey(9), (4,), 0,
                              cfg.vocab)
    prompts = [jnp.concatenate(
        [tmpl, jax.random.randint(jax.random.PRNGKey(20 + i),
                                  (1 + i % 3,), 0, cfg.vocab)])
        for i in range(8)]
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       steal=True, steal_poll_s=0.001)
    got = fleet(prompts, 6, slots=1)
    _assert_all_equal(got, _solo(params, prompts, 6, cfg))
    st = fleet.last_stats["fleet"]
    assert st["stolen"] >= 1, st
    # both replicas actually served work after the steal
    served_by = [r["requests"] for r in st["per_replica"]]
    assert all(s > 0 for s in served_by), served_by


def test_fleet_disaggregated_with_stealing_stays_exact():
    """Disaggregation + work stealing together: handoff adds land in
    decode queues WHILE the monitor steals between them (the race
    surface the claimed-candidate guard exists for) — every request
    must be served exactly once, solo-exact, with nothing lost."""
    cfg, params, prompts, max_len = _zipf_setup()
    fleet = make_fleet(params, cfg, max_len=max_len, replicas=4,
                       kv_block=4, share_prefix=True,
                       disaggregate=True, prefill_workers=2,
                       steal=True, steal_poll_s=0.0005)
    got = fleet(prompts, 5, slots=1)
    _assert_all_equal(got, _solo(params, prompts, 5, cfg))
    st = fleet.last_stats["fleet"]
    assert st["served"] == len(prompts) and st["shed"] == 0


def test_fleet_affinity_queue_bound_overrides_to_least_loaded():
    """The hotspot guard: with every prompt sharing one template and a
    tight affinity_queue_bound, the router must divert the overflow to
    the other replica AT ROUTING TIME (deterministic — steal off)."""
    cfg, params, _ = _setup()
    tmpl = jax.random.randint(jax.random.PRNGKey(10), (4,), 0,
                              cfg.vocab)
    prompts = [jnp.concatenate(
        [tmpl, jax.random.randint(jax.random.PRNGKey(30 + i),
                                  (1 + i % 2,), 0, cfg.vocab)])
        for i in range(6)]
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       affinity_queue_bound=2, est_token_s=0.05,
                       steal=False)
    got = fleet(prompts, 4, slots=2)
    _assert_all_equal(got, _solo(params, prompts, 4, cfg))
    st = fleet.last_stats["fleet"]
    served_by = [r["requests"] for r in st["per_replica"]]
    assert all(s > 0 for s in served_by), served_by
    # the diverted requests are billed as non-affinity placements
    assert st["affinity_routed_frac"] < 1.0


def test_fleet_sampled_colocated_placement_invariant():
    """Sampled serving through the fleet: token keys are (request,
    position)-derived, so ANY placement reproduces the single-engine
    sampled run exactly — the schedule-invariance contract surviving
    one more scheduler layer."""
    from nvidia_terraform_modules_tpu.models import make_sampler

    cfg, params, prompts = _setup()
    rng = jax.random.PRNGKey(7)
    sampler = make_sampler(temperature=0.8, top_k=4)
    single = make_serve_engine(params, cfg, max_len=16, kv_block=4,
                               sampler=sampler)
    want = single(prompts, 5, slots=2, rng=rng)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       sampler=sampler)
    got = fleet(prompts, 5, slots=2, rng=rng)
    _assert_all_equal(got, want)


def test_fleet_arrival_gated_matches_all_at_once():
    cfg, params, prompts = _setup()
    arrivals = poisson_trace(300.0, len(prompts), seed=6)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4)
    got = fleet(prompts, 6, slots=2, arrivals=arrivals)
    _assert_all_equal(got, _solo(params, prompts, 6, cfg))


def test_fleet_eos_early_stopping_matches_solo():
    """Per-request eos retirement composes with routing: variable
    output lengths, every request equals its solo decode truncated at
    its first eos."""
    cfg, params, prompts = _setup()
    full = _solo(params, prompts, 8, cfg)
    # an eos that actually appears mid-stream (derived from reference)
    eos = int(full[0][0])

    def truncate(seq):
        keep = []
        for t in seq:
            keep.append(t)
            if int(t) == eos:
                break
        return jnp.stack(keep)

    want = [truncate(f) for f in full]
    assert any(len(w) < 8 for w in want)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4)
    got = fleet(prompts, 8, slots=2, eos_id=eos)
    _assert_all_equal(got, want)


def test_fleet_stats_schema_and_telemetry_free_default():
    cfg, params, prompts = _setup()
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4)
    fleet(prompts, 4, slots=2)
    st = fleet.last_stats
    assert set(st) == {"fleet", "replica_stats"}
    f = st["fleet"]
    for key in ("replicas", "mode", "prefill_workers", "routing",
                "requests", "served", "shed", "shed_requests",
                "stolen", "affinity_routed_frac",
                "affinity_hit_blocks", "affinity_hit_frac",
                "prefill_tokens_saved", "deadline_attainment",
                "goodput_tokens", "latency_ms", "per_replica",
                "routed_to"):
        assert key in f, key
    assert f["latency_ms"]["p99"] >= f["latency_ms"]["p50"] > 0
    assert len(f["per_replica"]) == 2
    for r in f["per_replica"]:
        for key in ("role", "replica", "requests", "waves",
                    "occupancy", "kv_peak_blocks", "preempted"):
            assert key in r, key
    assert len(st["replica_stats"]) == 2
    assert f["goodput_tokens"] == 4 * len(prompts)


def test_fleet_validation():
    cfg, params, prompts = _setup()
    with pytest.raises(ValueError, match="replicas"):
        make_fleet(params, cfg, max_len=16, replicas=0)
    with pytest.raises(ValueError, match="routing"):
        make_fleet(params, cfg, max_len=16, routing="sticky")
    with pytest.raises(ValueError, match="2 replicas"):
        make_fleet(params, cfg, max_len=16, replicas=1,
                   disaggregate=True)
    with pytest.raises(ValueError, match="prefill_workers"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   disaggregate=True, prefill_workers=2)
    with pytest.raises(ValueError, match="greedy-only"):
        from nvidia_terraform_modules_tpu.models import make_sampler

        make_fleet(params, cfg, max_len=16, replicas=2,
                   disaggregate=True, sampler=make_sampler(top_k=2))
    with pytest.raises(ValueError, match="spec_k"):
        make_fleet(params, cfg, max_len=24, replicas=2,
                   disaggregate=True, spec_k=2)
    with pytest.raises(ValueError, match="est_token_s"):
        make_fleet(params, cfg, max_len=16, est_token_s=0.0)
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4)
    with pytest.raises(ValueError, match="est_token_s"):
        fleet(prompts, 4, deadlines=[1.0] * len(prompts))
    with pytest.raises(ValueError, match="deadlines"):
        shed_fleet = make_fleet(params, cfg, max_len=16, replicas=1,
                                kv_block=4, est_token_s=0.01)
        shed_fleet(prompts, 4, deadlines=[1.0])
    with pytest.raises(ValueError, match="arrivals"):
        fleet(prompts, 4, arrivals=[0.0])
    assert fleet([], 4) == []


def test_fleet_consistent_hash_ring_stability():
    """The consistent-hash property the ring exists for: growing the
    fleet by one replica moves only a minority of the keyspace, and
    equal keys always agree."""
    from nvidia_terraform_modules_tpu.models.fleet import (
        HashRing,
        affinity_key,
    )

    keys = [affinity_key(list(range(i, i + 8)), 4) for i in range(64)]
    r3, r4 = HashRing(3), HashRing(4)
    assert [r3.target(k) for k in keys] == [r3.target(k) for k in keys]
    moved = sum(r3.target(k) != r4.target(k) for k in keys)
    assert moved < len(keys) // 2, f"{moved}/{len(keys)} keys moved"
    # prompts sharing their first full block share a routing key;
    # sub-block prompts key on the whole string
    assert affinity_key([1, 2, 3, 4, 9], 4) \
        == affinity_key([1, 2, 3, 4, 7, 7], 4)
    assert affinity_key([1, 2], 4) != affinity_key([1, 3], 4)


def test_fleet_hash_ring_removal_symmetry():
    """The PR 13 recovery pin, mirror of the PR 12 grow pin: removing
    a replica (death or planned drain) moves ONLY the removed target's
    keyspace — every key it did not own keeps its assignment — and
    re-adding it restores the original assignment EXACTLY, for every
    member of a 4-target ring. This is what makes redrive placement
    (and the warm prefix indexes behind it) stable across a kill."""
    from nvidia_terraform_modules_tpu.models.fleet import (
        HashRing,
        affinity_key,
    )

    keys = [affinity_key(list(range(i, i + 8)), 4) for i in range(128)]
    base = HashRing(4)
    before = {k: base.target(k) for k in keys}
    for victim in range(4):
        ring = HashRing(4)
        ring.remove(victim)
        assert ring.targets() == {0, 1, 2, 3} - {victim}
        moved = 0
        for k in keys:
            t = ring.target(k)
            assert t != victim
            if before[k] == victim:
                moved += 1              # victim keyspace must move
            else:
                # a survivor's key NEVER moves on a removal
                assert t == before[k], (victim, before[k], t)
        assert moved == sum(1 for v in before.values() if v == victim)
        ring.add(victim)
        assert {k: ring.target(k) for k in keys} == before
    # guard rails: the last target is irremovable, double ops are loud
    solo = HashRing(1)
    with pytest.raises(ValueError, match="last ring target"):
        solo.remove(0)
    with pytest.raises(ValueError, match="not on the ring"):
        HashRing(2).remove(5)
    with pytest.raises(ValueError, match="already on the ring"):
        HashRing(2).add(1)
