# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Decode-attention kernels vs their jnp oracles, and the paged kernel
vs the gather path it supersedes.

The kernels (``ops/decode_attention.py``) run in interpret mode here.
Two distinct exactness bars, deliberately:

- vs the jnp paths (``_cached_attention`` / the ``forward_paged``
  gather): fp-tolerance, not bit equality — the online softmax
  re-orders the reduction;
- PAGED kernel vs the CONTIGUOUS kernel on the gathered logical view
  at equal tile size: BITWISE for f32/bf16 — both run the one shared
  ``_tile_fold`` over identical tile contents in identical order, so
  the block-table indirection must change addresses, never bits. The
  int8 sidecar fold is tight-tolerance instead: the paged kernel
  transposes scale tiles in-kernel (the contiguous wrapper pre-
  transposes — chip-tuned), and XLA may fuse the scale multiply
  differently around it (~1 ulp observed).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nvidia_terraform_modules_tpu.models.decode import quantize_kv
from nvidia_terraform_modules_tpu.ops.decode_attention import (
    int8_kv_decode_attention,
    kv_decode_attention,
    paged_decode_attention,
)


def _oracle(q, k8, ks, v8, vs, pos, scale):
    b, h, d = q.shape
    kv = k8.shape[2]
    k = k8.astype(jnp.float32) * ks[..., None]
    v = v8.astype(jnp.float32) * vs[..., None]
    qg = q.astype(jnp.float32).reshape(b, kv, h // kv, d)
    s = jnp.einsum("bkgd,bskd->bkgs", qg, k) * scale
    mask = jnp.arange(k.shape[1])[None] <= pos[:, None]      # [B, S]
    s = jnp.where(mask[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", p, v)
    return out.reshape(b, h, d)


def _setup(b, s, h, kv, d, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 4)
    q = jax.random.normal(ks[0], (b, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kv, d), jnp.float32)
    k8, k_s = quantize_kv(k)
    v8, v_s = quantize_kv(v)
    pos = jax.random.randint(ks[3], (b,), 0, s)
    return q, k8, k_s, v8, v_s, pos


@pytest.mark.parametrize("h,kv", [(4, 4), (8, 2)])
def test_matches_oracle_mha_and_gqa(h, kv):
    q, k8, ks, v8, vs, pos = _setup(3, 64, h, kv, 128)
    got = int8_kv_decode_attention(q, k8, ks, v8, vs, pos,
                                   scale=128 ** -0.5, block_s=32,
                                   interpret=True)
    want = _oracle(q, k8, ks, v8, vs, pos, 128 ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_odd_row_count_shrinks_block_to_divisor():
    # S=72 has no 32-divisor; the kernel must shrink to 8 (72 = 8×9)
    # rather than run a ragged tail block (whose clamped start would
    # silently read earlier rows under the mask)
    q, k8, ks, v8, vs, _ = _setup(2, 72, 4, 4, 128, key=1)
    pos = jnp.asarray([71, 70], jnp.int32)      # live keys reach the tail
    got = int8_kv_decode_attention(q, k8, ks, v8, vs, pos,
                                   scale=128 ** -0.5, block_s=32,
                                   interpret=True)
    want = _oracle(q, k8, ks, v8, vs, pos, 128 ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_indivisible_row_count_refuses():
    q, k8, ks, v8, vs, pos = _setup(1, 12, 4, 4, 128, key=4)
    with pytest.raises(ValueError, match="block divisor"):
        int8_kv_decode_attention(q, k8, ks, v8, vs, pos,
                                 scale=128 ** -0.5, interpret=True)


def test_early_positions_skip_dead_blocks():
    # pos=0: only the first key participates; later blocks are skipped
    q, k8, ks, v8, vs, _ = _setup(2, 96, 4, 4, 128, key=2)
    pos = jnp.asarray([0, 5], jnp.int32)
    got = int8_kv_decode_attention(q, k8, ks, v8, vs, pos,
                                   scale=128 ** -0.5, block_s=32,
                                   interpret=True)
    want = _oracle(q, k8, ks, v8, vs, pos, 128 ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_vmap_composes():
    # the serve engine vmaps single-row attention over the slot pool
    q, k8, ks, v8, vs, pos = _setup(4, 48, 4, 4, 128, key=3)
    f = lambda qq, kk, kss, vv, vss, pp: int8_kv_decode_attention(
        qq[None], kk[None], kss[None], vv[None], vss[None], pp[None],
        scale=128 ** -0.5, block_s=16, interpret=True)[0]
    got = jax.vmap(f)(q, k8, ks, v8, vs, pos)
    want = _oracle(q, k8, ks, v8, vs, pos, 128 ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_cached_attention_gate_routes_through_kernel():
    """The TPU-only dispatch glue in _cached_attention (q slicing, pos
    broadcast, output reshape) must stay testable off-chip: force the
    gate and pin greedy int8 decode against the jnp path's tokens."""
    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        greedy_decode,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models import decode as decode_mod

    cfg = BurnInConfig(vocab=64, d_model=256, n_heads=2, d_ff=64,
                       n_layers=2, seq_len=16, batch=2,
                       dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0,
                                cfg.vocab)
    want = greedy_decode(params, prompt, 6, cfg, cache_dtype="int8")
    decode_mod._FORCE_DECODE_KERNEL = True
    try:
        got = greedy_decode(params, prompt, 6, cfg, cache_dtype="int8")
    finally:
        decode_mod._FORCE_DECODE_KERNEL = False
    assert jnp.array_equal(want, got), (want, got)


def test_cached_attention_gate_falls_back_on_odd_rows():
    """A hand-built int8 cache whose row count has no 8-multiple block
    divisor (S=12) must fall through the forced gate to the jnp path —
    the kernel's trace-time ValueError is for direct callers only."""
    from nvidia_terraform_modules_tpu.models import decode as decode_mod
    from nvidia_terraform_modules_tpu.models.decode import (
        _cached_attention,
    )

    b, s, kv, d = 2, 12, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (b, 1, kv, d), jnp.float32)
    k8, k_s = quantize_kv(jax.random.normal(ks[1], (b, s, kv, d)))
    v8, v_s = quantize_kv(jax.random.normal(ks[2], (b, s, kv, d)))
    q_pos = jnp.asarray([s - 1], jnp.int32)
    want = _cached_attention(q, k8, v8, q_pos, d ** -0.5, k_s, v_s)
    decode_mod._FORCE_DECODE_KERNEL = True
    try:
        got = _cached_attention(q, k8, v8, q_pos, d ** -0.5, k_s, v_s)
    finally:
        decode_mod._FORCE_DECODE_KERNEL = False
    assert jnp.array_equal(got, want)


def test_cached_attention_gate_respects_int8_kernel_flag():
    """int8_kernel=False keeps the jnp path even when the forced gate
    would otherwise fire (the mesh-sharded-pool escape hatch)."""
    from nvidia_terraform_modules_tpu.models import decode as decode_mod
    from nvidia_terraform_modules_tpu.models.decode import (
        _cached_attention,
    )

    b, s, kv, d = 2, 32, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (b, 1, kv, d), jnp.float32)
    k8, k_s = quantize_kv(jax.random.normal(ks[1], (b, s, kv, d)))
    v8, v_s = quantize_kv(jax.random.normal(ks[2], (b, s, kv, d)))
    q_pos = jnp.asarray([s - 1], jnp.int32)
    want = _cached_attention(q, k8, v8, q_pos, d ** -0.5, k_s, v_s)
    decode_mod._FORCE_DECODE_KERNEL = True
    try:
        got = _cached_attention(q, k8, v8, q_pos, d ** -0.5, k_s, v_s,
                                int8_kernel=False)
    finally:
        decode_mod._FORCE_DECODE_KERNEL = False
    assert jnp.array_equal(got, want)


# ------------------------------------------------- paged decode kernel


def _paged_setup(b, h, kv, d, nb, bs, nt, key=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(key), 4)
    q = jax.random.normal(ks[0], (b, h, d), dtype)
    k_pool = jax.random.normal(ks[1], (nb, bs, kv, d), dtype)
    v_pool = jax.random.normal(ks[2], (nb, bs, kv, d), dtype)
    # out-of-order, non-contiguous physical blocks (never reserved 0)
    perm = jax.random.permutation(ks[3], jnp.arange(1, nb))
    tables = perm[:b * nt].reshape(b, nt).astype(jnp.int32)
    return q, k_pool, v_pool, tables


def _gathered(pool, tables):
    b, nt = tables.shape
    return pool[tables].reshape((b, nt * pool.shape[1]) + pool.shape[2:])


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_paged_kernel_bitwise_vs_contiguous_on_gathered_view(dtype):
    """THE paged-kernel contract: at equal tile size the block-table
    indirection is bitwise invisible — the paged kernel equals the
    contiguous kernel run on the materialised logical view, per dtype,
    across ragged per-row positions (pos=0 single-live-block included).
    """
    b, h, kv, d, nb, bs, nt = 3, 8, 2, 128, 16, 16, 4
    q, kp, vp, tables = _paged_setup(b, h, kv, d, nb, bs, nt,
                                     dtype=dtype)
    pos = jnp.asarray([nt * bs - 1, 17, 0], jnp.int32)
    got = paged_decode_attention(q, kp, vp, tables, pos,
                                 scale=d ** -0.5, interpret=True)
    want = kv_decode_attention(q, _gathered(kp, tables),
                               _gathered(vp, tables), pos,
                               scale=d ** -0.5, block_s=bs,
                               interpret=True)
    assert jnp.array_equal(got, want), (
        f"{dtype} paged vs gathered-contiguous diverged: "
        f"{jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32)).max()}")


def test_paged_kernel_int8_sidecars_tight_tol_vs_contiguous():
    """Int8 pools: the scale sidecars ride the same tables with
    in-kernel dequant. The paged scale tiles transpose in-kernel (the
    contiguous wrapper pre-transposes), so XLA may fuse the scale
    multiply differently — tight tolerance, not bits."""
    b, h, kv, d, nb, bs, nt = 3, 8, 2, 128, 16, 16, 4
    q, kp, vp, tables = _paged_setup(b, h, kv, d, nb, bs, nt, key=1)
    k8, ks = quantize_kv(kp)
    v8, vs = quantize_kv(vp)
    pos = jnp.asarray([nt * bs - 1, 21, 5], jnp.int32)
    got = paged_decode_attention(q, k8, v8, tables, pos,
                                 scale=d ** -0.5, k_scale=ks,
                                 v_scale=vs, interpret=True)
    want = kv_decode_attention(q, _gathered(k8, tables),
                               _gathered(v8, tables), pos,
                               scale=d ** -0.5,
                               k_scale=_gathered(ks, tables),
                               v_scale=_gathered(vs, tables),
                               block_s=bs, interpret=True)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("h,kv", [(4, 4), (8, 2), (4, 1)])
def test_paged_kernel_matches_jnp_gather_oracle(h, kv):
    """MHA, GQA and MQA against the dense-softmax oracle over the
    gathered view — the forward_paged gather path's math."""
    b, d, nb, bs, nt = 2, 128, 12, 8, 3
    q, kp, vp, tables = _paged_setup(b, h, kv, d, nb, bs, nt, key=2)
    pos = jnp.asarray([nt * bs - 2, 9], jnp.int32)
    got = paged_decode_attention(q, kp, vp, tables, pos,
                                 scale=d ** -0.5, interpret=True)
    kg, vg = _gathered(kp, tables), _gathered(vp, tables)
    ones = jnp.ones(kg.shape[:3])
    want = _oracle(q, kg, ones, vg, ones, pos, d ** -0.5)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_paged_kernel_dead_blocks_and_garbage_are_unreachable():
    """Recycled-block garbage must be bitwise invisible: scribbling
    over (a) every block not referenced by a live table entry and
    (b) every in-block row past each row's pos changes nothing — the
    exact fencing contract the serve engine's retirement relies on."""
    b, h, kv, d, nb, bs, nt = 2, 4, 2, 128, 10, 8, 3
    q, kp, vp, tables = _paged_setup(b, h, kv, d, nb, bs, nt, key=3)
    pos = jnp.asarray([11, 4], jnp.int32)
    base = paged_decode_attention(q, kp, vp, tables, pos,
                                  scale=d ** -0.5, interpret=True)
    # the permutation setup maps every (row, entry) to a DISTINCT
    # physical block, so per block the reachable rows are exactly the
    # one referencing row's live span — poison everything else
    kp2, vp2 = kp, vp
    referenced = set()
    for r in range(b):
        for i in range(nt):
            blk = int(tables[r, i])
            referenced.add(blk)
            live_rows = min(max(int(pos[r]) - i * bs + 1, 0), bs)
            if live_rows < bs:
                dead = jnp.arange(bs) >= live_rows
                kp2 = kp2.at[blk].set(jnp.where(dead[:, None, None],
                                                1e4, kp2[blk]))
                vp2 = vp2.at[blk].set(jnp.where(dead[:, None, None],
                                                1e4, vp2[blk]))
    for blk in set(range(nb)) - referenced:      # recycled elsewhere
        kp2 = kp2.at[blk].set(1e4)
        vp2 = vp2.at[blk].set(1e4)
    got = paged_decode_attention(q, kp2, vp2, tables, pos,
                                 scale=d ** -0.5, interpret=True)
    assert jnp.array_equal(got, base)


def test_paged_kernel_validation():
    q = jnp.zeros((2, 3, 128))                  # 3 heads over 2 kv
    kp = vp = jnp.zeros((4, 8, 2, 128))
    t = jnp.zeros((2, 2), jnp.int32)
    pos = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="multiple"):
        paged_decode_attention(q, kp, vp, t, pos, scale=1.0,
                               interpret=True)
    with pytest.raises(ValueError, match="together"):
        paged_decode_attention(jnp.zeros((2, 4, 128)), kp, vp, t, pos,
                               scale=1.0, k_scale=jnp.zeros((4, 8, 2)),
                               interpret=True)


# ---------------------------------------------------- lowering pins


def _all_eqns(jaxpr, out=None):
    """Recursively collect eqns from a (Closed)Jaxpr (PR 9 pin style)."""
    if out is None:
        out = []
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        out.append(eqn)
        for sub in eqn.params.values():
            subs = sub if isinstance(sub, (list, tuple)) else (sub,)
            for s in subs:
                if hasattr(s, "eqns") or hasattr(s, "jaxpr"):
                    _all_eqns(s, out)
    return out


def _paged_forward_fixture(cache_dtype="bf16"):
    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models.paging import (
        init_paged_cache,
    )

    cfg = BurnInConfig(vocab=64, d_model=256, n_heads=2, d_ff=64,
                       n_layers=2, seq_len=16, batch=2,
                       dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    pool = init_paged_cache(cfg, 2, 16, block_size=8, num_blocks=9,
                            cache_dtype=cache_dtype)
    pool["block_tables"] = jnp.asarray([[7, 2], [1, 5]], jnp.int32)
    pool["pos"] = jnp.asarray([5, 3], jnp.int32)
    return cfg, params, pool


@pytest.mark.parametrize("cache_dtype", ["bf16", "int8"])
def test_forward_paged_kernel_lowering_no_logical_gather(cache_dtype):
    """The de-paging pin: with ``paged_kernel="on"`` the T=1 step's
    jaxpr contains one pallas_call per layer and NO gather whose
    output is the ``[B, NT, bs, kv, D]`` logical view — a silent fall
    back to the gather path (re-introducing HBM traffic that scales
    with pool size) fails tier-1. The "off" side proves the detector
    sees the gathers it is meant to ban."""
    from nvidia_terraform_modules_tpu.models.decode import forward_paged

    cfg, params, pool = _paged_forward_fixture(cache_dtype)
    toks = jnp.zeros((2, 1), jnp.int32)
    b, nt = pool["block_tables"].shape
    bs = pool["k"][0].shape[1]
    view_elems = b * nt * bs * cfg.kv_heads * cfg.head_dim

    def eqns_for(mode):
        fn = lambda t, p: forward_paged(params, t, p, cfg,
                                        paged_kernel=mode)[0]
        return _all_eqns(jax.make_jaxpr(fn)(toks, pool))

    on = eqns_for("on")
    n_pallas = sum(e.primitive.name == "pallas_call" for e in on)
    assert n_pallas == cfg.n_layers, n_pallas

    def view_gathers(eqns):
        return [e for e in eqns if e.primitive.name == "gather"
                and int(np.prod(e.outvars[0].aval.shape)) == view_elems]

    assert not view_gathers(on), view_gathers(on)
    off = eqns_for("off")
    assert view_gathers(off), "detector lost the reference gathers"
    assert not any(e.primitive.name == "pallas_call" for e in off)
