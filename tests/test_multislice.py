# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Multi-slice (DCN) meshes: planning, device grouping, hierarchical training.

The virtual 8-device CPU rig stands in for 2×v5e-4 (or 4×v5e-2) multi-slice
deployments: the ``slice`` axis is the DCN hop, everything inside a slice is
ICI. SURVEY §5 maps the reference's "long-context" answer to slice scaling;
these tests prove the workload side composes across slices.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    init_params,
    make_train_step,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.parallel import (
    build_multislice_mesh,
    group_devices_by_slice,
    make_rules,
    plan_multislice,
)
from nvidia_terraform_modules_tpu.parallel.collectives import (
    psum_probe,
    ring_permute_probe,
)
from nvidia_terraform_modules_tpu.smoketest import run_smoketest


def test_plan_multislice_shapes():
    plan = plan_multislice(8, 2, tp=2, sp=1)
    assert plan.axis_names == ("slice", "dp", "sp", "tp")
    assert plan.shape == (2, 2, 1, 2)
    assert plan.n_devices == 8


def test_plan_multislice_rejects_uneven():
    with pytest.raises(ValueError, match="evenly divide"):
        plan_multislice(8, 3)


@dataclasses.dataclass
class _FakeDev:
    id: int
    slice_index: int


def test_grouping_prefers_slice_index_metadata():
    # interleaved enumeration must still land devices with their slice
    devs = [_FakeDev(i, slice_index=i % 2) for i in range(8)]
    groups = group_devices_by_slice(devs, 2)
    assert [d.slice_index for d in groups[0]] == [0] * 4
    assert [d.slice_index for d in groups[1]] == [1] * 4


def test_grouping_rejects_uneven_slices():
    devs = [_FakeDev(i, slice_index=0 if i < 5 else 1) for i in range(8)]
    with pytest.raises(ValueError, match="uneven"):
        group_devices_by_slice(devs, 2)


def test_grouping_falls_back_to_chunks_without_metadata(jax8):
    groups = group_devices_by_slice(jax8.devices(), 4)
    assert [len(g) for g in groups] == [2, 2, 2, 2]


def test_build_multislice_mesh(jax8):
    mesh = build_multislice_mesh(n_slices=2)
    assert mesh.axis_names == ("slice", "dp", "sp", "tp")
    assert mesh.shape["slice"] == 2
    assert mesh.devices.size == 8


def test_rules_shard_batch_over_slice_and_dp(jax8):
    mesh = build_multislice_mesh(n_slices=2)
    rules = make_rules(mesh)
    assert rules.data == ("slice", "dp")
    assert rules.batch == P(("slice", "dp"))


def test_dcn_psum_and_ici_ring(jax8):
    """psum over the DCN axis and ring over an intra-slice axis both pass."""
    mesh = build_multislice_mesh(plan_multislice(8, 2, tp=2))
    r = psum_probe(mesh, axis="slice", n_elems=1 << 10)
    assert r["ok"] and r["participants"] == 2
    r = ring_permute_probe(mesh, axis="tp", n_elems=1 << 10)
    assert r["ok"]


def test_multislice_train_step_decreases_loss(jax8):
    mesh = build_multislice_mesh(plan_multislice(8, 2, tp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                       seq_len=16, batch=8)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(8):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_multislice_forward_matches_unsharded(jax8):
    mesh = build_multislice_mesh(plan_multislice(8, 2, tp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=8, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg)
    ref = forward(params, tokens, cfg)
    got = forward(
        init_params(jax.random.PRNGKey(0), cfg, rules),
        jax.device_put(tokens, rules.shard(rules.act(None))), cfg, rules)
    assert jnp.max(jnp.abs(ref - got)) < 1e-5


def test_multislice_ring_attention_train(jax8):
    """sp ring inside each slice while dp spans slices (hierarchy composes)."""
    mesh = build_multislice_mesh(plan_multislice(8, 2, tp=1, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=8, attn="ring")
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    params, l0 = step(params, batch)
    for _ in range(5):
        params, l1 = step(params, batch)
    assert float(l1) < float(l0)


def test_grouping_fallback_rejects_indivisible(jax8):
    with pytest.raises(ValueError, match="evenly divide"):
        group_devices_by_slice(jax8.devices(), 3)


def test_smoketest_bad_slice_config_fails_cleanly(jax8):
    """A bad slice count must fail the JSON contract, not crash it."""
    res = run_smoketest(level="psum", env={"TPU_SMOKETEST_SLICES": "3"})
    assert not res.ok
    assert "evenly divide" in res.checks["slices_error"]
    res = run_smoketest(level="psum", env={"TPU_SMOKETEST_SLICES": "two"})
    assert not res.ok and "slices_error" in res.checks


def test_smoketest_multislice_env(jax8):
    res = run_smoketest(level="probes", env={"TPU_SMOKETEST_SLICES": "2"})
    assert res.ok
    assert res.checks["slices"] == 2
    assert res.checks["dcn_psum_ok"]
    assert res.checks["dcn_psum_participants"] == 2
    assert res.checks["mesh"]["slice"] == 2


# --------------------------------------------- elastic worlds over DCN
# (the elastic-multislice tentpole: the slice count is a variable — the
# hierarchical psum and the mesh planner both re-trace to whatever
# topology the resumed world actually has)


def _hier_sum(mesh, x):
    """Run hierarchical_psum over a replicated input inside shard_map."""
    import functools

    from nvidia_terraform_modules_tpu.parallel import hierarchical_psum
    from nvidia_terraform_modules_tpu.utils.compat import shard_map

    def kernel():
        i = jnp.float32(0.0)
        for a in ("slice", "dp"):
            if a in mesh.axis_names:
                i = i * mesh.shape[a] + \
                    jax.lax.axis_index(a).astype(jnp.float32)
        return hierarchical_psum(x + i, mesh)

    # check_vma=False: replication of the RS→AR→AG composition is real
    # but not statically inferrable (same situation as the pallas calls)
    return jax.jit(functools.partial(
        shard_map, mesh=mesh, in_specs=(), out_specs=P(),
        check_vma=False)(kernel))()


def _expected(mesh, x):
    import numpy as np

    m = 1
    for a in ("slice", "dp"):
        if a in mesh.axis_names:
            m *= mesh.shape[a]
    return m * np.asarray(x) + m * (m - 1) / 2


def test_hierarchical_psum_matches_flat_sum(jax8):
    """RS(ICI) → AR(DCN on 1/k) → AG(ICI) must equal the flat psum over
    (slice × dp) — including the padding path (element count not
    divisible by the inner degree)."""
    import numpy as np

    mesh = build_multislice_mesh(plan_multislice(8, 2, tp=2))  # dp=2
    for shape in ((8,), (5, 3)):   # 15 elements: pad for k=2
        x = jnp.arange(float(np.prod(shape))).reshape(shape)
        out = _hier_sum(mesh, x)
        np.testing.assert_allclose(np.asarray(out), _expected(mesh, x),
                                   rtol=1e-6)


def test_hierarchical_psum_tolerates_missing_or_unit_slice_axis(jax8):
    """The elastic contract: after a shrink the re-formed mesh may have
    slice == 1 (or no slice axis at all) — the same call degrades to the
    plain ICI psum instead of tracing a dead DCN stage."""
    import numpy as np

    from nvidia_terraform_modules_tpu.parallel import (
        build_mesh,
        plan_elastic_multislice,
        plan_mesh,
    )

    x = jnp.arange(6.0)
    # slice axis of size 1 (the degenerate multislice plan)
    m1 = build_multislice_mesh(plan_elastic_multislice(8, 1, tp=2))
    np.testing.assert_allclose(np.asarray(_hier_sum(m1, x)),
                               _expected(m1, x), rtol=1e-6)
    # no slice axis at all (a plain single-slice mesh)
    m2 = build_mesh(plan_mesh(8, tp=2))
    np.testing.assert_allclose(np.asarray(_hier_sum(m2, x)),
                               _expected(m2, x), rtol=1e-6)


def test_hierarchical_psum_probe_on_multislice_mesh(jax8):
    from nvidia_terraform_modules_tpu.parallel import (
        hierarchical_psum_probe,
    )

    mesh = build_multislice_mesh(plan_multislice(8, 2, tp=2))
    r = hierarchical_psum_probe(mesh, n_elems=1 << 10)
    assert r["ok"], r
    assert r["participants"] == 4          # 2 slices × dp 2
    assert r["dcn_bytes"] > 0 and r["ici_bytes"] > r["dcn_bytes"]


def test_plan_elastic_multislice_shrinks_to_feasible_slice_count():
    from nvidia_terraform_modules_tpu.parallel import (
        plan_elastic_multislice,
    )

    # full fleet: preferred count fits
    assert plan_elastic_multislice(8, 2, tp=2).shape[0] == 2
    # a whole slice died: 4 devices still form 2 slices of 2
    assert plan_elastic_multislice(4, 2, tp=1).shape[0] == 2
    # odd survivor count: 6 devices, preferred 4 → 3 slices of 2
    assert plan_elastic_multislice(6, 4, tp=1).shape[0] == 3
    # last survivor: degenerate but still slice-shaped
    p = plan_elastic_multislice(1, 2)
    assert p.axis_names[0] == "slice" and p.shape[0] == 1
    with pytest.raises(ValueError):
        plan_elastic_multislice(8, 0)


def test_smoketest_reports_hierarchical_psum(jax8):
    res = run_smoketest(level="psum", env={"TPU_SMOKETEST_SLICES": "2"})
    assert res.ok
    assert res.checks["hier_psum_ok"]
    assert res.checks["hier_psum_participants"] == 2
