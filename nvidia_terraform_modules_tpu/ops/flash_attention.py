# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Pallas TPU flash attention: pipelined, block-sparse, O(S) memory, custom VJP.

The hot op of the burn-in workload (and of any transformer a provisioned slice
will run) is attention. XLA already fuses elementwise chains into the matmuls;
what it does NOT do is tile the softmax(QKᵀ)V contraction so the [S, S] score
matrix never materialises in HBM. That is this kernel's job — the classic
flash-attention recurrence, written for the MXU/VMEM model of the pallas guide
(`/opt/skills/guides/pallas_guide.md`):

- grid (batch·heads, q-blocks, k-steps); k innermost so the f32 accumulators
  (o, m, l) live in VMEM scratch across the k sweep;
- block matmuls run in the input dtype on the MXU (bf16 in production) with
  ``preferred_element_type=f32`` accumulation; the online softmax runs on the
  VPU in f32;
- masking is block-sparse ("splash"): a precomputed per-(q-block, k-block)
  liveness map rides into the kernel as a tiny SMEM input and dead tiles are
  skipped with ``pl.when`` (no FLOPs, no mask materialisation) — in the
  forward AND in both backward paths;
- the backward pass recomputes P = exp(S - L) per tile from the saved
  logsumexp L (flash-style rematerialisation: trade FLOPs for HBM).

Software pipeline (``pipeline="auto"|"on"|"off"``)
--------------------------------------------------

PROFILE_r05 priced the post-retune ceiling: the flash kernels ran at ~0.40
MXU fraction because the online-softmax VPU work (rowmax, exp, rescale) of
tile *i* serialised against the MXU dots of tile *i+1*. The pipelined kernels
break that serialisation structurally: each k grid step consumes a PAIR of
k sub-tiles whose score dots are issued back-to-back **before** either
sub-tile's VPU fold, so Mosaic can keep the MXU busy on sub-tile *i+1*'s
QKᵀ while the VPU folds sub-tile *i* (and the doubled K/V block window gives
the DMA pipeline the same lookahead). The fold itself is arithmetically
IDENTICAL to the unpipelined kernel's — same sub-tile order, same ops — so
``pipeline="on"`` bit-matches ``pipeline="off"`` at equal block sizes; the
smoke test (``flash_pipeline_ok``) and a tier-1 lowering pin keep that
property honest. ``"auto"`` (default) pipelines whenever the K tiling has an
even number of blocks.

A fully-masked sub-tile folds as an exact identity (corr = 1, Σp = 0), which
is what lets the pipelined kernel fold a dead half of a half-live pair and
still bit-match the unpipelined kernel that skipped it outright.

VMEM-budget autoshrink
----------------------

Default block sizes are no longer a table: ``auto_blocks`` picks the q block
by the measured v5e rule (``min(1024, max(128, S/4))``) and then the WIDEST
K block whose deterministic VMEM plan (double-buffered block windows +
scratch accumulators + in-flight f32 score tiles, ``flash_vmem_bytes``) fits
``FLASH_VMEM_BUDGET`` (16 MiB/core). The plan reproduces the measured
round-5 defaults (S=4096, d=128 → 1024×1024 unpipelined; 2048-wide tiles
rejected exactly as they failed to compile on chip) and computes wider K for
narrow heads (d=64 → 2048) instead of capping at the table's 1024. The
pipelined kernels hold two K sub-tiles in flight, so the same budget lands
them at half the K width (S=4096, d=128 → 1024×512 pairs) — identical bytes
streamed per step, double the lookahead.

Splash masking (``mask=``)
--------------------------

``MaskSpec`` generalises the old causal-only block skip: ``"causal"``,
``"full"``, or ``("window", W)`` (sliding causal window) compile to a
per-(q-block, k-block) liveness map — DEAD tiles are skipped in forward and
backward, PARTIAL tiles apply the element mask, FULL tiles fold unmasked
(the element mask is still applied to them, which is a bitwise no-op, so
causal numerics are unchanged from the pre-splash kernels). The map is a
host-side numpy constant (``block_liveness``) threaded through the
``custom_vjp``; ``splash_stats`` reports the dead/partial/full tile split
for bench capture (``flash_splash_skip_frac``).

Backward: fused single-pass (default) vs split
----------------------------------------------

Two selectable backward implementations, ``backward="fused"|"split"``:

- ``"split"`` (the historical design): two kernels — dq, then (dk, dv) —
  each sweeping the full (q-block × k-block) grid and each calling
  ``_bwd_tile``, so the tile scores P and dS are rematerialised TWICE per
  tile. Kept for A/B timing and the differential oracle; never pipelined.
- ``"fused"`` (default): ONE ``pallas_call`` sweeping the grid once,
  computing P/dS once per tile and emitting all three gradients:

  * **dq** accumulates across the K dimension in a ``[block_q, d]`` f32
    VMEM scratch over the inner k sweep and is cast + written once per
    q-block at the last k step;
  * **dk/dv** accumulate across the Q dimension in full-K-length
    ``[nk, block_k, d]`` f32 VMEM scratches that persist across the whole
    grid sweep, and each k-block's slice is cast + written during the LAST
    q row, so every output block's cast/write-back DMA overlaps the next
    tile's dots via pallas's double-buffered output pipeline;
  * with ``pipeline`` on, each grid step processes a k sub-tile PAIR with
    all four MXU front dots (two QKᵀ, two dO·Vᵀ) hoisted ahead of the VPU
    dS work — the same overlap story as the forward;
  * dead tiles are skipped via the splash liveness map.

  The full-length dk/dv scratch costs ``2 · S_k · d · 4`` bytes of VMEM
  (4 MiB at the flagship S=4096, d=128); very long K at wide d would need a
  k-sharded outer loop, which ring attention already provides — and the
  ring's per-visiting-block backward reuses these kernels (pipelined fused
  by default), so the S≫4096 flagship composes both.

A lowering-regression test pins the backward ``pallas_call`` count AND the
pipelined grid shape, so a silent fallback to the split or unpipelined path
can never masquerade as a perf win.

CPU runs (tests, the virtual-mesh rig) use ``interpret=True`` automatically.
Chip-capture protocol for retunes: see "Kernel tuning" in
``gke-tpu/README.md``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# block-liveness classes in the splash map
MASK_DEAD = 0      # no live element: tile skipped, zero FLOPs
MASK_PARTIAL = 1   # straddles the mask edge: element mask applies
MASK_FULL = 2      # every element live

# per-core VMEM the kernels may plan against (v5e/v4 class); the autoshrink
# rejects block shapes whose deterministic plan exceeds it
FLASH_VMEM_BUDGET = 16 * 1024 * 1024
K_BLOCK_CAP = 2048


def _on_interpret_platform() -> bool:
    return jax.devices()[0].platform != "tpu"


# ------------------------------------------------------------- mask specs

@dataclasses.dataclass(frozen=True)
class MaskSpec:
    """Static attention-mask description, hashable so it can thread through
    ``custom_vjp`` nondiff args and the liveness-map cache.

    kind: ``"causal"`` (q ≥ k), ``"full"`` (no mask), or ``"window"``
    (sliding causal window: q ≥ k and q - k < window).
    """

    kind: str = "causal"
    window: int | None = None

    def __post_init__(self):
        if self.kind not in ("causal", "full", "window"):
            raise ValueError(
                f"unknown mask kind {self.kind!r}; use causal|full|window")
        if self.kind == "window":
            if self.window is None or self.window < 1:
                raise ValueError(
                    f"window mask needs window >= 1, got {self.window}")
        elif self.window is not None:
            raise ValueError(f"mask kind {self.kind!r} takes no window")


def as_mask_spec(mask, causal: bool = True) -> MaskSpec:
    """Normalise the public ``mask=`` argument: ``None`` defers to the
    ``causal`` flag; a string names a kind; ``("window", W)`` and
    ``MaskSpec`` pass through validated."""
    if mask is None:
        return MaskSpec("causal" if causal else "full")
    if isinstance(mask, MaskSpec):
        return mask
    if isinstance(mask, str):
        return MaskSpec(mask)
    if isinstance(mask, tuple) and len(mask) == 2 and mask[0] == "window":
        return MaskSpec("window", int(mask[1]))
    raise ValueError(
        f"unknown mask {mask!r}; use None, 'causal'|'full', ('window', W) "
        f"or a MaskSpec")


@functools.lru_cache(maxsize=256)
def block_liveness(spec: MaskSpec, nq: int, nk: int,
                   block_q: int, block_k: int) -> np.ndarray:
    """Per-(q-block, k-block) liveness map — the splash mask.

    Generalises the old ``_causal_live`` arithmetic predicate to any static
    mask spec: ``[nq, nk] int32`` of MASK_DEAD / MASK_PARTIAL / MASK_FULL,
    computed host-side once per (spec, tiling) and fed to the kernels as an
    SMEM input so every grid step reads its class with one scalar load.
    """
    if spec.kind == "full":
        live = np.full((nq, nk), MASK_FULL, np.int32)
    else:
        qlo = np.arange(nq, dtype=np.int64)[:, None] * block_q
        qhi = qlo + block_q - 1
        klo = np.arange(nk, dtype=np.int64)[None, :] * block_k
        khi = klo + block_k - 1
        dead = klo > qhi                      # strictly above the diagonal
        full = khi <= qlo                     # wholly at-or-below it
        if spec.kind == "window":
            w = spec.window
            dead |= khi < qlo - (w - 1)       # wholly older than the window
            full &= (qhi - klo) <= (w - 1)    # newest q still sees oldest k
        live = np.where(dead, MASK_DEAD,
                        np.where(full, MASK_FULL, MASK_PARTIAL)).astype(
                            np.int32)
    live.setflags(write=False)
    return live


def _liveness_for_grid(spec: MaskSpec, nq: int, nk: int, block_q: int,
                       block_k: int, pipe: bool) -> jnp.ndarray:
    """Liveness as the kernel grid sees it: per sub-tile normally, collapsed
    to per-PAIR (max of the two halves) for the pipelined kernels."""
    live = block_liveness(spec, nq, nk, block_q, block_k)
    if pipe:
        live = live.reshape(nq, nk // 2, 2).max(axis=-1)
    return jnp.asarray(live)


def splash_stats(spec: MaskSpec, s_q: int, s_k: int,
                 block_q: int, block_k: int) -> dict:
    """Dead/partial/full tile split of the splash map at a tiling — the
    bench-capture number (``flash_splash_skip_frac`` = dead / total)."""
    live = block_liveness(spec, s_q // block_q, s_k // block_k,
                          block_q, block_k)
    total = live.size
    dead = int((live == MASK_DEAD).sum())
    return {
        "total": total,
        "dead": dead,
        "partial": int((live == MASK_PARTIAL).sum()),
        "full": int((live == MASK_FULL).sum()),
        "skip_frac": round(dead / max(total, 1), 4),
    }


def mask_live_frac(spec: MaskSpec, s: int) -> float:
    """Fraction of the [S, S] score matrix the mask keeps live — the FLOP
    billing factor for MFU accounting. Causal keeps the historical 0.5
    convention (``train_step_flops`` billed S²/2 long before splash)."""
    if spec.kind == "full":
        return 1.0
    if spec.kind == "causal":
        return 0.5
    w = min(spec.window, s)
    live = w * (w + 1) // 2 + (s - w) * w
    return live / float(s * s)


# ------------------------------------------------------------ tile math

def _tile_scores(q, k, qi, ki, *, scale, spec: MaskSpec,
                 block_q, block_k):
    """Scaled, mask-applied f32 scores for one (q-block × k-block) tile.

    Shared by the forward and both backward paths so masking/precision can
    never drift between them. The matmul keeps the input dtype on the MXU
    and accumulates f32; the scale is applied to the f32 scores. The element
    mask is applied to every non-full-kind tile (a bitwise no-op on fully
    live tiles), so PARTIAL vs FULL never changes the traced code.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale          # [bq, bk]
    if spec.kind != "full":
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        keep = q_pos >= k_pos
        if spec.kind == "window":
            keep = jnp.logical_and(keep, q_pos - k_pos < spec.window)
        s = jnp.where(keep, s, NEG_INF)
    return s


def _masked_exp(s, ref):
    """exp(s - ref) with fully-masked entries forced to 0 (not exp(0))."""
    p = jnp.exp(s - ref)
    return jnp.where(s <= NEG_INF / 2, 0.0, p)


# ---------------------------------------------------------------- forward

def _fold_scores(s, v, m_scr, l_scr, acc_scr):
    """ONE online-softmax fold of precomputed scores ``s`` against values
    ``v``, updating the VMEM scratch state in place. The single definition
    of the numerically sensitive update — shared by the normalising forward,
    the partial (ring) forward, and both pipeline modes, so their numerics
    can never drift. Folding a fully-masked tile is a bitwise identity
    (corr = 1, Σp = 0), which is what makes the pipelined kernels' identity
    folds of dead pair-halves exact."""
    m_prev, l_prev = m_scr[:], l_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = _masked_exp(s, m_new)
    corr = jnp.exp(m_prev - m_new)
    l_scr[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [bq, d]
    m_scr[:] = m_new


def _fwd_sweep(live_ref, q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr, *,
               scale, spec, block_q, block_k, pipe):
    """Init + fold(s) for one forward grid step, shared by the normalising
    and partial kernels. With ``pipe`` the K/V window holds a sub-tile PAIR
    and both score dots are issued before either fold — the software
    pipeline: the MXU runs sub-tile i+1's QKᵀ while the VPU folds i."""
    qi, kj = pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(live_ref[0, 0] != MASK_DEAD)
    def _compute():
        q = q_ref[0]
        if not pipe:
            s = _tile_scores(q, k_ref[0], qi, kj, scale=scale, spec=spec,
                             block_q=block_q, block_k=block_k)
            _fold_scores(s, v_ref[0], m_scr, l_scr, acc_scr)
        else:
            k0, k1 = k_ref[0, :block_k], k_ref[0, block_k:]
            # both MXU dots issue BEFORE either sub-tile's VPU fold
            s0 = _tile_scores(q, k0, qi, 2 * kj, scale=scale, spec=spec,
                              block_q=block_q, block_k=block_k)
            s1 = _tile_scores(q, k1, qi, 2 * kj + 1, scale=scale, spec=spec,
                              block_q=block_q, block_k=block_k)
            _fold_scores(s0, v_ref[0, :block_k], m_scr, l_scr, acc_scr)
            _fold_scores(s1, v_ref[0, block_k:], m_scr, l_scr, acc_scr)


def _fwd_kernel(live_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, spec: MaskSpec,
                block_q: int, block_k: int, pipe: bool):
    _fwd_sweep(live_ref, q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
               scale=scale, spec=spec, block_q=block_q, block_k=block_k,
               pipe=pipe)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _fwd_partial_kernel(live_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                        m_scr, l_scr, acc_scr, *, scale: float,
                        spec: MaskSpec, block_q: int, block_k: int,
                        pipe: bool):
    """Forward WITHOUT the final normalisation: emits the raw online-softmax
    state (unnormalised accumulator, running max, running sum) so an outer
    fold — ring attention's per-shard combine — can merge blocks exactly."""
    _fwd_sweep(live_ref, q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
               scale=scale, spec=spec, block_q=block_q, block_k=block_k,
               pipe=pipe)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = acc_scr[:]
        m_ref[0] = m_scr[:]
        l_ref[0] = l_scr[:]


def _fwd_in_specs(d, block_q, block_k, pipe):
    """Input specs shared by both forward kernels: splash map in SMEM, then
    q / k / v block windows (K/V doubled when pipelined)."""
    kw = 2 * block_k if pipe else block_k
    return [
        pl.BlockSpec((1, 1), lambda b, i, j: (i, j),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, kw, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, kw, d), lambda b, i, j: (b, j, 0)),
    ]


def _fwd(q, k, v, *, scale, spec, block_q, block_k, pipe, interpret):
    bh, s, d = q.shape
    sk = k.shape[1]
    nq, nk = s // block_q, sk // block_k
    if pipe:
        assert nk % 2 == 0, "pipelined forward needs an even K tiling"
    live = _liveness_for_grid(spec, nq, nk, block_q, block_k, pipe)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, spec=spec,
        block_q=block_q, block_k=block_k, pipe=pipe)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk // 2 if pipe else nk),
        in_specs=_fwd_in_specs(d, block_q, block_k, pipe),
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            # [bh, s, 1]: trailing singleton keeps the block TPU-tileable
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running normaliser l
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(live, q, k, v)
    return o, lse


# -------------------------------------------------- partial forward (ring)

def flash_partial(q, k, v, *, scale: float, causal: bool,
                  block_q: int, block_k: int, interpret: bool,
                  mask=None, pipeline: bool = False):
    """One flash sweep of ``q``×(``k``,``v``) in ``[bh, s, d]`` layout,
    returning the UNNORMALISED state ``(o_acc f32, m f32, l f32)`` with
    shapes ``[bh, sq, d], [bh, sq, 1], [bh, sq, 1]``.

    ``k``/``v`` may have a different sequence length than ``q`` (ring
    attention feeds one visiting K/V block per call); ``causal`` masks in
    LOCAL positions, which is exactly right for the ring's diagonal block
    (q and k share the same global offset there) and unused for its
    fully-visible blocks. ``pipeline`` runs the paired-sub-tile kernel
    (requires an even K tiling).
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    spec = as_mask_spec(mask, causal)
    nq, nk = sq // block_q, sk // block_k
    if pipeline:
        assert nk % 2 == 0, "pipelined flash_partial needs an even K tiling"
    live = _liveness_for_grid(spec, nq, nk, block_q, block_k, pipeline)
    kernel = functools.partial(
        _fwd_partial_kernel, scale=scale, spec=spec,
        block_q=block_q, block_k=block_k, pipe=pipeline)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk // 2 if pipeline else nk),
        in_specs=_fwd_in_specs(d, block_q, block_k, pipeline),
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(live, q, k, v)


# ------------------------------------------------------------- backward

def _bwd_tile(q, k, v, do, lse, delta, qi, ki, *,
              scale, spec, block_q, block_k):
    """Rematerialised P and dS for one tile (shared by the split kernels)."""
    s = _tile_scores(q, k, qi, ki, scale=scale, spec=spec,
                     block_q=block_q, block_k=block_k)
    p = _masked_exp(s, lse)                                  # [bq, bk]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)                                    # [bq, bk] f32
    return p, ds


def _dq_kernel(live_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, acc_scr, *, scale: float, spec: MaskSpec,
               block_q: int, block_k: int):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(live_ref[0, 0] != MASK_DEAD)
    def _compute():
        _, ds = _bwd_tile(q_ref[0], k_ref[0], v_ref[0], do_ref[0],
                          lse_ref[0], delta_ref[0], qi, ki, scale=scale,
                          spec=spec, block_q=block_q, block_k=block_k)
        acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = (acc_scr[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(live_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                spec: MaskSpec, block_q: int, block_k: int):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(live_ref[0, 0] != MASK_DEAD)
    def _compute():
        do = do_ref[0]
        p, ds = _bwd_tile(q_ref[0], k_ref[0], v_ref[0], do,
                          lse_ref[0], delta_ref[0], qi, ki, scale=scale,
                          spec=spec, block_q=block_q, block_k=block_k)
        # dV += Pᵀ dO
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dK += dSᵀ Q  (scale applied at finalize)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = (dv_scr[:]).astype(dv_ref.dtype)


def _fused_sub_tile(s, dp, do, q, k, lse, delta, ki, dq_scr, dk_scr, dv_scr):
    """VPU dS + the three gradient accumulations for one sub-tile of the
    fused backward, given the (possibly hoisted) MXU front dots ``s``/``dp``.
    A fully-masked sub-tile contributes exact zeros (P = 0 ⇒ dS = 0), so
    folding it is a bitwise identity on every accumulator."""
    p = _masked_exp(s, lse)
    ds = p * (dp - delta)
    # dQ += dS K: folded over the inner k sweep, like the forward's o
    dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # dV[ki] += Pᵀ dO, dK[ki] += dSᵀ Q: folded over the outer q sweep
    dv_scr[ki] = dv_scr[ki] + jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dk_scr[ki] = dk_scr[ki] + jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fused_bwd_kernel(live_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dk_ref, dv_ref,
                      dq_scr, dk_scr, dv_scr, *, scale: float,
                      spec: MaskSpec, block_q: int, block_k: int,
                      pipe: bool):
    """Single-pass backward: dq, dk, dv from ONE sweep of the (qi, kj) grid.

    P/dS are materialised once per tile and feed all three accumulators.
    dq lives in a per-q-block scratch across the inner k sweep; dk/dv live
    in full-K-length scratches across the outer q sweep (slice ``ki`` per
    sub-tile) and each k-block is emitted on the last q row, so every output
    block's cast/write-back overlaps the next tile's dots via the output
    pipeline's double buffering. With ``pipe`` each grid step consumes a k
    sub-tile PAIR with all four MXU front dots hoisted ahead of the VPU dS
    work (see the module docstring).
    """
    qi, kj = pl.program_id(1), pl.program_id(2)
    nq, nkg = pl.num_programs(1), pl.num_programs(2)

    @pl.when(kj == 0)
    def _init_dq():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(jnp.logical_and(qi == 0, kj == 0))
    def _init_dkv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(live_ref[0, 0] != MASK_DEAD)
    def _compute():
        q, do = q_ref[0], do_ref[0]
        lse, delta = lse_ref[0], delta_ref[0]
        if not pipe:
            k, v = k_ref[0], v_ref[0]
            s = _tile_scores(q, k, qi, kj, scale=scale, spec=spec,
                             block_q=block_q, block_k=block_k)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            _fused_sub_tile(s, dp, do, q, k, lse, delta, kj,
                            dq_scr, dk_scr, dv_scr)
        else:
            k0, k1 = k_ref[0, :block_k], k_ref[0, block_k:]
            v0, v1 = v_ref[0, :block_k], v_ref[0, block_k:]
            # all four MXU front dots issue BEFORE either sub-tile's VPU
            # dS work — the backward half of the software pipeline
            s0 = _tile_scores(q, k0, qi, 2 * kj, scale=scale, spec=spec,
                              block_q=block_q, block_k=block_k)
            s1 = _tile_scores(q, k1, qi, 2 * kj + 1, scale=scale, spec=spec,
                              block_q=block_q, block_k=block_k)
            dp0 = jax.lax.dot_general(do, v0, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            dp1 = jax.lax.dot_general(do, v1, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            _fused_sub_tile(s0, dp0, do, q, k0, lse, delta, 2 * kj,
                            dq_scr, dk_scr, dv_scr)
            _fused_sub_tile(s1, dp1, do, q, k1, lse, delta, 2 * kj + 1,
                            dq_scr, dk_scr, dv_scr)

    @pl.when(kj == nkg - 1)
    def _emit_dq():
        dq_ref[0] = (dq_scr[:] * scale).astype(dq_ref.dtype)

    # the full accumulation for each k slice is complete once the last q row
    # has run; earlier rows' write-backs of the rotating output block are
    # dead stores the final row overwrites — the price of letting the
    # pipeline overlap them. (Emission is unconditional on liveness: a
    # dead (last-row, k) tile still owns its slice's write-back.)
    @pl.when(qi == nq - 1)
    def _emit_dkv():
        if not pipe:
            dk_ref[0] = (dk_scr[kj] * scale).astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[kj].astype(dv_ref.dtype)
        else:
            dk_ref[0, :block_k] = (dk_scr[2 * kj] * scale).astype(
                dk_ref.dtype)
            dk_ref[0, block_k:] = (dk_scr[2 * kj + 1] * scale).astype(
                dk_ref.dtype)
            dv_ref[0, :block_k] = dv_scr[2 * kj].astype(dv_ref.dtype)
            dv_ref[0, block_k:] = dv_scr[2 * kj + 1].astype(dv_ref.dtype)


# ------------------------------------------------------ public wrapper

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_bhsd(q, k, v, scale, spec, block_q, block_k, interpret,
                backward, pipe):
    o, _ = _fwd(q, k, v, scale=scale, spec=spec,
                block_q=block_q, block_k=block_k, pipe=pipe,
                interpret=interpret)
    return o


def _flash_bhsd_fwd(q, k, v, scale, spec, block_q, block_k, interpret,
                    backward, pipe):
    o, lse = _fwd(q, k, v, scale=scale, spec=spec,
                  block_q=block_q, block_k=block_k, pipe=pipe,
                  interpret=interpret)
    return o, (q, k, v, o, lse)


def flash_dq(q, k, v, do, lse, delta, *, scale, causal, block_q, block_k,
             interpret, mask=None, out_dtype=None):
    """dQ for ``q``×(``k``,``v``) in ``[bh, s, d]`` layout; reusable by the
    ring backward (per visiting K/V block, f32 out for cross-step
    accumulation) and the monolithic VJP below."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    spec = as_mask_spec(mask, causal)
    nq, nk = sq // block_q, sk // block_k
    live = _liveness_for_grid(spec, nq, nk, block_q, block_k, False)
    return pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, spec=spec,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i, j: (i, j),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), out_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(live, q, k, v, do, lse, delta)


def flash_dkv(q, k, v, do, lse, delta, *, scale, causal, block_q, block_k,
              interpret, mask=None, out_dtype=None):
    """(dK, dV) in ``[bh, s, d]`` layout; see ``flash_dq``."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    spec = as_mask_spec(mask, causal)
    nq, nk = sq // block_q, sk // block_k
    live = _liveness_for_grid(spec, nq, nk, block_q, block_k, False)
    return pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, spec=spec,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, j, i: (i, j),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(live, q, k, v, do, lse, delta)


def flash_dqdkv(q, k, v, do, lse, delta, *, scale, causal, block_q, block_k,
                interpret, mask=None, pipeline: bool = False,
                out_dtype=None):
    """(dQ, dK, dV) from the fused single-pass kernel, ``[bh, s, d]`` layout.

    One ``pallas_call``: P/dS once per tile instead of the split path's
    twice; see ``_fused_bwd_kernel``. ``pipeline`` runs the paired-sub-tile
    software-pipelined body (requires an even K tiling). Reusable by the
    ring backward (per visiting K/V block, f32 out for cross-step
    accumulation) and the monolithic VJP below.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    spec = as_mask_spec(mask, causal)
    nq, nk = sq // block_q, sk // block_k
    if pipeline:
        assert nk % 2 == 0, "pipelined flash_dqdkv needs an even K tiling"
    live = _liveness_for_grid(spec, nq, nk, block_q, block_k, pipeline)
    kw = 2 * block_k if pipeline else block_k
    return pl.pallas_call(
        functools.partial(_fused_bwd_kernel, scale=scale, spec=spec,
                          block_q=block_q, block_k=block_k, pipe=pipeline),
        grid=(bh, nq, nk // 2 if pipeline else nk),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i, j: (i, j),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, kw, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, kw, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, kw, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, kw, d), lambda b, i, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),       # dq accumulator
            pltpu.VMEM((nk, block_k, d), jnp.float32),   # dk, full K length
            pltpu.VMEM((nk, block_k, d), jnp.float32),   # dv, full K length
        ],
        interpret=interpret,
    )(live, q, k, v, do, lse, delta)


def flash_backward(q, k, v, o, do, lse, *, scale, causal=True, block_q,
                   block_k, interpret, backward: str = "fused",
                   mask=None, pipeline: bool = False, out_dtype=None):
    """Full flash backward — delta reduction + the selected kernel path.

    The one entry point both the monolithic VJP and callers that hold their
    own residuals use; ``backward`` picks ``"fused"`` (single pass,
    optionally pipelined) or ``"split"`` (dq then dkv, the historical
    two-kernel design — never pipelined).
    """
    # delta = rowsum(dO ⊙ O): a cheap fused XLA reduction, computed once
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                     # [bh, s, 1]
    if backward not in ("fused", "split"):
        # validate here too, not only in flash_attention: a typo falling
        # through to the split kernels would be a silent de-optimisation
        raise ValueError(
            f"unknown backward impl {backward!r}; use fused|split")
    kw = dict(scale=scale, causal=causal, mask=mask, block_q=block_q,
              block_k=block_k, interpret=interpret, out_dtype=out_dtype)
    if backward == "fused":
        return flash_dqdkv(q, k, v, do, lse, delta, pipeline=pipeline, **kw)
    dq = flash_dq(q, k, v, do, lse, delta, **kw)
    dk, dv = flash_dkv(q, k, v, do, lse, delta, **kw)
    return dq, dk, dv


def _flash_bhsd_bwd(scale, spec, block_q, block_k, interpret, backward,
                    pipe, res, do):
    q, k, v, o, lse = res
    return flash_backward(q, k, v, o, do, lse, scale=scale, mask=spec,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, backward=backward,
                          pipeline=pipe)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


# ------------------------------------------------- block-size selection

def _fit_block(s: int, want: int | None) -> int:
    """Largest divisor of ``s`` ≤ ``want`` that is a multiple of 8; ``None``
    picks a size by S.

    Measured on v5e (in-jit delta timing, flagship [2, S, 16, 128]):
    fatter tiles win decisively at long S — at S=4096, 1024×1024 blocks
    run the causal forward 2.0× faster than 512×512 (1.74 vs 3.41 ms,
    0.40 vs 0.21 MXU fraction) and the backward 1.4× (3.64 vs 5.17 ms);
    at S=2048 the 512×1024 shape wins; 2048-blocks fail to compile
    (VMEM). The None default is therefore ``min(1024, max(128, S/4))``
    — the q-block rule; the K default is budget-computed by
    ``auto_blocks`` (widest K whose VMEM plan fits). Candidates step down
    in units of 8 (the f32 sublane) so a non-tileable divisor like 125
    (S=250) — which compiles under CPU interpret but real-TPU pallas
    rejects or badly pads — can never be picked; sequences with no
    8-multiple divisor get the ValueError path in ``flash_attention``
    ("pad the sequence") instead.
    """
    if want is None:
        want = min(1024, max(128, s // 4))
    if s <= 8:
        return s  # tiny test shapes; interpret mode only
    b = min(want - want % 8, s - s % 8)
    while b >= 8 and s % b:
        b -= 8
    return b if b >= 8 else 0


def flash_fwd_vmem_bytes(block_q: int, block_k: int, d: int, itemsize: int,
                         *, pipe: bool) -> int:
    """Deterministic VMEM plan of the forward kernel at a block shape:
    double-buffered block windows (K/V doubled under the pipeline), the
    f32 scratch accumulators, and the in-flight f32 score tiles (two when
    pipelined — the hoisted dot is the pipeline's footprint cost)."""
    kw = (2 if pipe else 1) * block_k
    win = (2 * block_q * d * itemsize          # q in
           + 2 * kw * d * itemsize * 2         # k, v in
           + 2 * block_q * d * itemsize        # o out
           + 2 * block_q * 4)                  # lse out
    scr = 2 * block_q * 4 + block_q * d * 4    # m, l, o accumulator
    tiles = (2 if pipe else 1) * block_q * block_k * 4
    return win + scr + tiles


def flash_bwd_vmem_bytes(block_q: int, block_k: int, s_k: int, d: int,
                         itemsize: int, *, pipe: bool) -> int:
    """VMEM plan of the fused backward — the binding kernel of a train
    step: adds the dO/dQ/dK/dV windows and the full-K-length f32 dk/dv
    scratches (``2·S_k·d·4`` bytes) to the forward's costs."""
    kw = (2 if pipe else 1) * block_k
    win = (2 * block_q * d * itemsize * 3      # q, do in; dq out
           + 2 * kw * d * itemsize * 4         # k, v in; dk, dv out
           + 2 * block_q * 4 * 2)              # lse, delta in
    scr = block_q * d * 4 + 2 * s_k * d * 4    # dq acc + full-K dk/dv
    tiles = (2 if pipe else 1) * block_q * block_k * 4
    return win + scr + tiles


def flash_vmem_bytes(block_q: int, block_k: int, s_k: int, d: int,
                     itemsize: int, *, pipe: bool) -> int:
    """Worst-kernel VMEM plan for a train step at a block shape."""
    return max(
        flash_fwd_vmem_bytes(block_q, block_k, d, itemsize, pipe=pipe),
        flash_bwd_vmem_bytes(block_q, block_k, s_k, d, itemsize, pipe=pipe))


def auto_blocks(s: int, d: int, itemsize: int, *, pipe: bool,
                want_q: int | None = None,
                budget: int | None = None) -> tuple[int, int, bool]:
    """VMEM-budget-aware default block selection → (block_q, block_k,
    pipelined).

    block_q follows the measured v5e q rule (``_fit_block(s, None)``);
    block_k is the WIDEST 8-multiple divisor of S ≤ ``K_BLOCK_CAP`` with at
    least two K blocks whose ``flash_vmem_bytes`` plan fits the budget —
    the old ``S/2``-cap-1024 table entry becomes a computed consequence.
    With ``pipe`` only even K tilings qualify (the kernel consumes sub-tile
    pairs); if none fits, the selection retries unpipelined and reports
    ``pipelined=False`` so ``pipeline="auto"`` degrades instead of failing.
    """
    budget = FLASH_VMEM_BUDGET if budget is None else budget
    if s <= 8:
        return _fit_block(s, want_q), s, False
    bq0 = _fit_block(s, want_q)
    if bq0 < 8:
        return bq0, 0, False      # no tileable divisor: caller raises
    k_top = min(s // 2, K_BLOCK_CAP)
    k_top -= k_top % 8            # candidates must stay sublane-aligned
    k_cands = [b for b in range(k_top, 7, -8) if s % b == 0]
    if not k_cands:
        return bq0, 0, False
    q_cands = ([bq0] if want_q is not None else
               [b for b in range(bq0, 7, -8) if s % b == 0])
    for bq in q_cands:
        for bk in k_cands:
            if pipe and (s // bk) % 2:
                continue
            if flash_vmem_bytes(bq, bk, s, d, itemsize,
                                pipe=pipe) <= budget:
                return bq, bk, pipe
    if pipe:
        # no even-nk tiling fits: degrade to the unpipelined selection
        bq, bk, _ = auto_blocks(s, d, itemsize, pipe=False, want_q=want_q,
                                budget=budget)
        return bq, bk, False
    # nothing fits the budget (pathological d): smallest legal blocks
    return q_cands[-1], k_cands[-1], False


def _resolve_pipeline(pipeline: str, s: int, block_k: int, *,
                      block_q: int = 0, d: int = 0, itemsize: int = 0,
                      s_k: int | None = None) -> bool:
    """Feasibility of the paired-sub-tile kernels at FITTED explicit blocks.

    ``"auto"`` additionally requires the PIPELINED VMEM plan to fit the
    budget (the doubled K/V window is not free: 1024×1024 explicit blocks
    at S=4096, d=128 fit serial but overflow pipelined — auto must degrade
    to serial there, exactly like ``auto_blocks`` would). ``"on"`` is an
    explicit operator demand and only enforces the structural even-tiling
    requirement — the budget is a planning model, and block sweeps need to
    be able to probe past it deliberately.
    """
    if pipeline == "off":
        return False
    nk = (s // block_k) if block_k else 0
    feasible = s > 8 and block_k >= 8 and nk >= 2 and nk % 2 == 0
    if pipeline == "on":
        if not feasible:
            raise ValueError(
                f"pipeline='on' needs an even number of K blocks (>= 2); "
                f"block_k={block_k} gives {nk} over seq len {s} — pass an "
                f"even-tiling block_k or pad the sequence")
        return True
    if feasible and block_q and d and itemsize:
        feasible = flash_vmem_bytes(
            block_q, block_k, s_k if s_k is not None else s, d, itemsize,
            pipe=True) <= FLASH_VMEM_BUDGET
    return feasible


def flash_attention(q, k, v, *, causal: bool = True, scale: float | None = None,
                    block_q: int | None = None, block_k: int | None = None,
                    interpret: bool | None = None,
                    backward: str = "fused",
                    pipeline: str = "auto",
                    mask=None):
    """Fused flash attention on ``[B, S, H, D]`` inputs (burn-in layout).

    Blocks default to the VMEM-budget selection (``auto_blocks``) and shrink
    to the largest divisor of S ≤ the requested size, so any sequence length
    works; sizes that leave no MXU-tileable divisor (< 8 for an S > 8) are
    rejected. ``backward`` selects the VJP kernels: ``"fused"`` (default;
    one single-pass pallas kernel, P/dS once per tile) or ``"split"`` (the
    historical dq + dkv two-kernel path, kept for A/B timing and the
    differential-correctness oracle). ``pipeline`` selects the
    software-pipelined paired-sub-tile kernels: ``"auto"`` (default; on
    whenever the K tiling has an even number of blocks), ``"on"`` (raise if
    infeasible), ``"off"`` — on/off bit-match at equal block sizes. ``mask``
    is a splash mask spec (``None`` defers to ``causal``; ``"causal"``,
    ``"full"``, ``("window", W)`` or a :class:`MaskSpec`): dead blocks are
    skipped at block granularity in forward and backward. Returns
    ``[B, S, H, D]`` in the input dtype.
    """
    b, s, h, d = q.shape
    if backward not in ("fused", "split"):
        raise ValueError(
            f"unknown backward impl {backward!r}; use fused|split")
    if pipeline not in ("auto", "on", "off"):
        raise ValueError(
            f"unknown pipeline mode {pipeline!r}; use auto|on|off")
    spec = as_mask_spec(mask, causal)
    itemsize = jnp.dtype(q.dtype).itemsize
    if block_k is None:
        want_pipe = pipeline != "off"
        block_q, block_k, pipe = auto_blocks(
            s, d, itemsize, pipe=want_pipe, want_q=block_q)
        if pipeline == "on" and not pipe:
            raise ValueError(
                f"pipeline='on': seq len {s} has no even K tiling inside "
                f"the VMEM budget — pass block_k explicitly or pad")
    else:
        block_q, block_k = _fit_block(s, block_q), _fit_block(s, block_k)
        pipe = _resolve_pipeline(pipeline, s, block_k, block_q=block_q,
                                 d=d, itemsize=itemsize)
    if s > 8 and (block_q < 8 or block_k < 8):
        raise ValueError(
            f"seq len {s} has no block divisor in [8, 128]; pad the sequence")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _on_interpret_platform()
    if not interpret and (block_q % 8 or block_k % 8):
        # tiny s <= 8 shapes pass _fit_block for interpret-mode tests, but
        # real-TPU mosaic rejects sub-sublane blocks — fail with the
        # actionable error instead of a raw compile failure
        raise ValueError(
            f"blocks ({block_q}, {block_k}) are not 8-multiples; real-TPU "
            f"pallas needs sublane-aligned blocks — pad the sequence")

    def to_bhsd(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    o = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), scale, spec,
                    block_q, block_k, interpret, backward, pipe)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def pick_impl(impl: str | None, seq_len: int, what: str) -> str:
    """Shared flash/dense tile-math selection for the sharded attention
    wrappers (ring, Ulysses). ``impl=None`` picks "flash" when ``seq_len``
    (the length the LOCAL attention problem runs at) tiles into 8-multiple
    blocks, "dense" otherwise — so shapes that worked pre-flash keep
    working; an explicit impl is validated and passed through."""
    if impl not in (None, "dense", "flash"):
        raise ValueError(f"unknown {what} impl {impl!r}; use dense|flash")
    if impl is not None:
        return impl
    return "flash" if (seq_len <= 8 and _on_interpret_platform()) or \
        _fit_block(seq_len, None) >= 8 else "dense"
