# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Multi-slice meshes: data parallelism over DCN, model axes inside ICI.

A single TPU slice gets its fast interconnect (ICI) from the ``gke-tpu``
node pool's ``placement_policy { tpu_topology }``; *between* slices there is
only the data-center network (DCN) — ordinary VPC networking, the analogue of
the reference's node-to-node security-group rules
(``/root/reference/eks/main.tf:28-49``). The scaling-book recipe for that
asymmetry: put the bandwidth-light axis (data-parallel gradient psum, which
overlaps with backward compute) across DCN, and keep bandwidth-hungry axes
(tp/sp activation collectives) inside a slice.

This module plans a 4-axis mesh ``("slice", "dp", "sp", "tp")`` where the
``slice`` axis maps device groups slice-by-slice, so XLA emits hierarchical
collectives: intra-slice reductions ride ICI, the cross-slice hop rides DCN
once per step. On real multi-slice hardware devices carry a ``slice_index``
attribute (populated by the megascale runtime the ``tpu_slices`` Terraform
layer provisions); test rigs fall back to contiguous grouping.
"""

from __future__ import annotations

import collections
from typing import Sequence

from .mesh import MeshPlan, plan_mesh


def plan_multislice(
    n_devices: int,
    n_slices: int,
    *,
    tp: int | None = None,
    sp: int = 1,
) -> MeshPlan:
    """Factorise ``n_devices`` over ``n_slices`` DCN groups × (dp, sp, tp) ICI.

    The per-slice factorisation reuses :func:`plan_mesh`, so tp stays the
    innermost (fastest-ICI) axis; ``slice`` is outermost — the only axis whose
    collectives cross DCN.
    """
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    if n_devices % n_slices:
        raise ValueError(
            f"{n_slices} slices do not evenly divide {n_devices} devices")
    per = plan_mesh(n_devices // n_slices, tp=tp, sp=sp)
    return MeshPlan(("slice",) + per.axis_names, (n_slices,) + per.shape)


def plan_elastic_multislice(
    n_devices: int,
    preferred_slices: int,
    *,
    tp: int | None = None,
    sp: int = 1,
) -> MeshPlan:
    """The mesh planner for a world whose size *changed* between resumes.

    An elastic restart cannot assume the configured slice count still
    matches the fleet: a spot reclaim may have taken whole slices (or
    hosts of one), and the re-formed world has whatever devices the
    survivors contribute. This picks the **largest feasible slice count
    ≤ preferred** that evenly divides the surviving device count and
    factorises (``plan_multislice``), degrading to a single-slice plan
    when nothing larger fits — so the 4-axis ``("slice", …)`` mesh (and
    therefore the sharding rules' ``("slice", "dp")`` data axes and the
    hierarchical-psum call sites) stay *structurally identical* across
    every world size, only the axis sizes re-trace. Growth is the same
    call with the returned capacity's device count.
    """
    if preferred_slices < 1:
        raise ValueError(
            f"preferred_slices must be >= 1, got {preferred_slices}")
    last_err: Exception | None = None
    for s in range(min(preferred_slices, n_devices), 0, -1):
        if n_devices % s:
            continue
        try:
            return plan_multislice(n_devices, s, tp=tp, sp=sp)
        except ValueError as exc:   # per-slice factorisation infeasible
            last_err = exc
    raise ValueError(
        f"no slice count in [1, {preferred_slices}] factorises "
        f"{n_devices} devices (tp={tp}, sp={sp}): {last_err}")


def group_devices_by_slice(devices: Sequence, n_slices: int) -> list[list]:
    """Order devices slice-major: real ``slice_index`` if present, else chunks.

    Pure function so the grouping policy is testable without TPU hardware.
    """
    if n_slices == 1:
        return [list(devices)]
    indices = [getattr(d, "slice_index", None) for d in devices]
    if all(i is not None for i in indices):
        groups: dict[int, list] = collections.defaultdict(list)
        for d, i in zip(devices, indices):
            groups[i].append(d)
        if len(groups) != n_slices:
            raise ValueError(
                f"devices report {len(groups)} distinct slice_index values, "
                f"expected {n_slices}")
        sizes = {len(g) for g in groups.values()}
        if len(sizes) != 1:
            raise ValueError(f"uneven slices: sizes {sorted(sizes)}")
        return [groups[i] for i in sorted(groups)]
    # CPU rigs / single-slice backends: contiguous chunks stand in for slices
    if len(devices) % n_slices:
        raise ValueError(
            f"{n_slices} slices do not evenly divide {len(devices)} devices")
    per = len(devices) // n_slices
    return [list(devices[i * per:(i + 1) * per]) for i in range(n_slices)]


def build_multislice_mesh(plan: MeshPlan | None = None, *,
                          n_slices: int | None = None, devices=None):
    """Materialise the 4-axis mesh; slice-major device order.

    Either ``plan`` (from :func:`plan_multislice`) or ``n_slices`` must be
    given. On real multi-slice hardware (devices expose ``slice_index``) the
    layout is delegated to ``mesh_utils.create_hybrid_device_mesh`` so
    in-slice axes follow the physical torus (logical tp neighbours are ICI
    neighbours); rigs without slice metadata fall back to contiguous
    grouping, where ordering carries no physical meaning.
    """
    import jax
    import numpy as np
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if plan is None:
        if n_slices is None:
            raise ValueError("pass plan= or n_slices=")
        plan = plan_multislice(len(devices), n_slices)
    if plan.axis_names[0] != "slice":
        raise ValueError(f"not a multislice plan: axes {plan.axis_names}")
    n_slices = plan.shape[0]
    if plan.n_devices != len(devices):
        raise ValueError(
            f"plan wants {plan.n_devices} devices, got {len(devices)}")
    per_shape = plan.shape[1:]
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (1,) + per_shape, (n_slices,) + (1,) * len(per_shape),
            devices=devices)
    else:
        groups = group_devices_by_slice(devices, n_slices)
        dev_array = np.stack(
            [np.asarray(g, dtype=object).reshape(per_shape) for g in groups])
    return Mesh(dev_array, plan.axis_names)


def dcn_slice_count(devices=None) -> int:
    """How many slices the visible devices span (1 on single-slice rigs)."""
    import jax

    if devices is None:
        devices = jax.devices()
    indices = {getattr(d, "slice_index", None) for d in devices}
    if None in indices:
        return 1
    return max(len(indices), 1)
