# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Pallas TPU flash attention: fused, tiled, O(S) memory, custom VJP.

The hot op of the burn-in workload (and of any transformer a provisioned slice
will run) is attention. XLA already fuses elementwise chains into the matmuls;
what it does NOT do is tile the softmax(QKᵀ)V contraction so the [S, S] score
matrix never materialises in HBM. That is this kernel's job — the classic
flash-attention recurrence, written for the MXU/VMEM model of the pallas guide
(`/opt/skills/guides/pallas_guide.md`):

- grid (batch·heads, q-blocks, k-blocks); k innermost so the f32 accumulators
  (o, m, l) live in VMEM scratch across the k sweep;
- block matmuls run in the input dtype on the MXU (bf16 in production) with
  ``preferred_element_type=f32`` accumulation; the online softmax runs on the
  VPU in f32;
- causal masking is block-sparse: k-blocks strictly above the diagonal are
  skipped with ``pl.when`` (no FLOPs, no mask materialisation);
- the backward pass recomputes P = exp(S - L) per tile from the saved
  logsumexp L (flash-style rematerialisation: trade FLOPs for HBM).

Backward: fused single-pass (default) vs split
----------------------------------------------

Two selectable backward implementations, ``backward="fused"|"split"``:

- ``"split"`` (the historical design): two kernels — dq, then (dk, dv) —
  each sweeping the full (q-block × k-block) grid and each calling
  ``_bwd_tile``, so the tile scores P and dS are rematerialised TWICE per
  tile. PROFILE_r05 priced this double rematerialisation (plus the f32
  epilogue traffic) as the bulk of the ~0.11 MFU between the measured 0.698
  ``burnin_mfu`` and the config's ~0.81 hardware ceiling.
- ``"fused"`` (default): ONE ``pallas_call`` sweeping the grid
  ``(bh, q-blocks, k-blocks)`` once, computing P/dS once per tile and
  emitting all three gradients. Accumulation scheme:

  * **dq** accumulates across the K dimension in a ``[block_q, d]`` f32
    VMEM scratch over the inner k sweep (k innermost, exactly like the
    forward) and is cast + written once per q-block at ``ki == nk-1``;
  * **dk/dv** accumulate across the Q dimension in full-K-length
    ``[nk, block_k, d]`` f32 VMEM scratches that persist across the whole
    grid sweep (each (qi, ki) tile adds into slice ``ki``), and each
    k-block's slice is cast + written during the LAST q-row sweep
    (``qi == nq-1``, where every k-block is causally live);
  * the f32 epilogue is thereby pipelined: dk/dv output blocks rotate
    every grid step, so pallas's double-buffered output pipeline overlaps
    each tile's accumulator cast/write-back DMA with the next tile's MXU
    dots instead of serialising a whole-array epilogue after the sweep —
    the "double-buffered epilogue" PROFILE_r05 called for;
  * causally dead tiles are skipped via the shared ``_causal_live``
    predicate, same as the forward.

  The full-length dk/dv scratch costs ``2 · S_k · d · 4`` bytes of VMEM
  (4 MiB at the flagship S=4096, d=128 — comfortably inside the ~16 MiB
  budget next to the ~1.5 MiB of double-buffered block windows); very long
  K at wide d would need a k-sharded outer loop, which ring attention
  already provides.

``"split"`` stays in-tree so A/B timing (``bench.py: flash_bwd_*``) and the
fused-vs-split differential oracle (tests/test_flash_attention.py) both keep
running; a lowering-regression test pins fused to exactly one backward
``pallas_call`` so a silent fallback can never masquerade as a perf win.

CPU runs (tests, the virtual-mesh rig) use ``interpret=True`` automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _on_interpret_platform() -> bool:
    return jax.devices()[0].platform != "tpu"


def _tile_scores(q_ref, k_ref, qi, ki, *, scale, causal, block_q, block_k):
    """Scaled, causally-masked f32 scores for one (q-block × k-block) tile.

    Shared by the forward and both backward kernels so masking/precision can
    never drift between them. The matmul keeps the input dtype on the MXU and
    accumulates f32; the scale is applied to the f32 scores.
    """
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale          # [bq, bk]
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return s


def _masked_exp(s, ref):
    """exp(s - ref) with fully-masked entries forced to 0 (not exp(0))."""
    p = jnp.exp(s - ref)
    return jnp.where(s <= NEG_INF / 2, 0.0, p)


def _causal_live(qi, ki, *, causal, block_q, block_k):
    """Python-level predicate: does block (qi, ki) intersect the causal mask?

    Evaluated on traced grid ids → returns a traced bool for ``pl.when``;
    k-blocks strictly above the diagonal are skipped entirely.
    """
    if not causal:
        return True
    return ki * block_k <= qi * block_q + block_q - 1


# ---------------------------------------------------------------- forward

def _online_softmax_step(q_ref, k_ref, v_ref, qi, ki, m_scr, l_scr, acc_scr,
                         *, scale, causal, block_q, block_k):
    """ONE (q-block × k-block) fold of the flash recurrence, updating the
    VMEM scratch state in place. The single definition of the numerically
    sensitive update — shared by the normalising forward and the partial
    (ring) forward so their numerics can never drift."""
    s = _tile_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                     block_q=block_q, block_k=block_k)
    m_prev, l_prev = m_scr[:], l_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = _masked_exp(s, m_new)
    corr = jnp.exp(m_prev - m_new)
    l_scr[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [bq, d]
    m_scr[:] = m_new


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                block_q: int, block_k: int):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_causal_live(qi, ki, causal=causal, block_q=block_q,
                          block_k=block_k))
    def _compute():
        _online_softmax_step(q_ref, k_ref, v_ref, qi, ki,
                             m_scr, l_scr, acc_scr, scale=scale,
                             causal=causal, block_q=block_q, block_k=block_k)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    bh, s, d = q.shape
    nq, nk = s // block_q, s // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    grid = (bh, nq, nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            # [bh, s, 1]: trailing singleton keeps the block TPU-tileable
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),   # running normaliser l
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# -------------------------------------------------- partial forward (ring)

def _fwd_partial_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                        m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                        block_q: int, block_k: int):
    """Forward WITHOUT the final normalisation: emits the raw online-softmax
    state (unnormalised accumulator, running max, running sum) so an outer
    fold — ring attention's per-shard combine — can merge blocks exactly."""
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_causal_live(qi, ki, causal=causal, block_q=block_q,
                          block_k=block_k))
    def _compute():
        _online_softmax_step(q_ref, k_ref, v_ref, qi, ki,
                             m_scr, l_scr, acc_scr, scale=scale,
                             causal=causal, block_q=block_q, block_k=block_k)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = acc_scr[:]
        m_ref[0] = m_scr[:]
        l_ref[0] = l_scr[:]


def flash_partial(q, k, v, *, scale: float, causal: bool,
                  block_q: int, block_k: int, interpret: bool):
    """One flash sweep of ``q``×(``k``,``v``) in ``[bh, s, d]`` layout,
    returning the UNNORMALISED state ``(o_acc f32, m f32, l f32)`` with
    shapes ``[bh, sq, d], [bh, sq, 1], [bh, sq, 1]``.

    ``k``/``v`` may have a different sequence length than ``q`` (ring
    attention feeds one visiting K/V block per call); ``causal`` masks in
    LOCAL positions, which is exactly right for the ring's diagonal block
    (q and k share the same global offset there) and unused for its
    fully-visible blocks.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    kernel = functools.partial(
        _fwd_partial_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ------------------------------------------------------------- backward

def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki, *,
              scale, causal, block_q, block_k):
    """Rematerialised P and dS for one tile (shared by dq and dk/dv)."""
    s = _tile_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                     block_q=block_q, block_k=block_k)
    p = _masked_exp(s, lse_ref[0])                           # [bq, bk]
    do = do_ref[0]
    dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])                             # [bq, bk] f32
    return p, ds, do


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_scr, *, scale: float, causal: bool,
               block_q: int, block_k: int):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_causal_live(qi, ki, causal=causal, block_q=block_q,
                          block_k=block_k))
    def _compute():
        _, ds, _ = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                             qi, ki, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k)
        acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = (acc_scr[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool, block_q: int, block_k: int):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_causal_live(qi, ki, causal=causal, block_q=block_q,
                          block_k=block_k))
    def _compute():
        p, ds, do = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                              qi, ki, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k)
        # dV += Pᵀ dO
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dK += dSᵀ Q  (scale applied at finalize)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _fused_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dq_scr, dk_scr, dv_scr, *,
                      scale: float, causal: bool, block_q: int, block_k: int):
    """Single-pass backward: dq, dk, dv from ONE sweep of the (qi, ki) grid.

    P/dS are materialised once per tile and feed all three accumulators.
    dq lives in a per-q-block scratch across the inner k sweep; dk/dv live
    in full-K-length scratches across the outer q sweep (slice ``ki`` per
    tile) and each k-block is emitted on the last q row, so every output
    block's cast/write-back overlaps the next tile's dots via the output
    pipeline's double buffering (see the module docstring).
    """
    qi, ki = pl.program_id(1), pl.program_id(2)
    nq, nk = pl.num_programs(1), pl.num_programs(2)

    @pl.when(ki == 0)
    def _init_dq():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(jnp.logical_and(qi == 0, ki == 0))
    def _init_dkv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_causal_live(qi, ki, causal=causal, block_q=block_q,
                          block_k=block_k))
    def _compute():
        p, ds, do = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                              qi, ki, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k)
        # dQ += dS K: folded over the inner k sweep, like the forward's o
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dV[ki] += Pᵀ dO, dK[ki] += dSᵀ Q: folded over the outer q sweep
        dv_scr[ki] = dv_scr[ki] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[ki] = dk_scr[ki] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _emit_dq():
        dq_ref[0] = (dq_scr[:] * scale).astype(dq_ref.dtype)

    # every k-block is live on the last q row (causal or not), so the full
    # accumulation for slice ki is complete exactly when (nq-1, ki) runs;
    # earlier rows' write-backs of this rotating block are dead stores the
    # final row overwrites — the price of letting the pipeline overlap them
    @pl.when(qi == nq - 1)
    def _emit_dkv():
        dk_ref[0] = (dk_scr[ki] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[ki].astype(dv_ref.dtype)


# ------------------------------------------------------ public wrapper

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k, interpret,
                backward):
    o, _ = _fwd(q, k, v, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, interpret=interpret)
    return o


def _flash_bhsd_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                    backward):
    o, lse = _fwd(q, k, v, scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, interpret=interpret)
    return o, (q, k, v, o, lse)


def flash_dq(q, k, v, do, lse, delta, *, scale, causal, block_q, block_k,
             interpret, out_dtype=None):
    """dQ for ``q``×(``k``,``v``) in ``[bh, s, d]`` layout; reusable by the
    ring backward (per visiting K/V block, f32 out for cross-step
    accumulation) and the monolithic VJP below."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    return pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), out_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)


def flash_dkv(q, k, v, do, lse, delta, *, scale, causal, block_q, block_k,
              interpret, out_dtype=None):
    """(dK, dV) in ``[bh, s, d]`` layout; see ``flash_dq``."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    return pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)


def flash_dqdkv(q, k, v, do, lse, delta, *, scale, causal, block_q, block_k,
                interpret, out_dtype=None):
    """(dQ, dK, dV) from the fused single-pass kernel, ``[bh, s, d]`` layout.

    One ``pallas_call``: P/dS once per tile instead of the split path's
    twice; see ``_fused_bwd_kernel``. Reusable by the ring backward (per
    visiting K/V block, f32 out for cross-step accumulation) and the
    monolithic VJP below.
    """
    bh, sq, d = q.shape
    sk = k.shape[1]
    nk = sk // block_k
    return pl.pallas_call(
        functools.partial(_fused_bwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, sq // block_q, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),       # dq accumulator
            pltpu.VMEM((nk, block_k, d), jnp.float32),   # dk, full K length
            pltpu.VMEM((nk, block_k, d), jnp.float32),   # dv, full K length
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)


def flash_backward(q, k, v, o, do, lse, *, scale, causal, block_q, block_k,
                   interpret, backward: str = "fused", out_dtype=None):
    """Full flash backward — delta reduction + the selected kernel path.

    The one entry point both the monolithic VJP and callers that hold their
    own residuals use; ``backward`` picks ``"fused"`` (single pass) or
    ``"split"`` (dq then dkv, the historical two-kernel design).
    """
    # delta = rowsum(dO ⊙ O): a cheap fused XLA reduction, computed once
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                     # [bh, s, 1]
    if backward not in ("fused", "split"):
        # validate here too, not only in flash_attention: a typo falling
        # through to the split kernels would be a silent de-optimisation
        raise ValueError(
            f"unknown backward impl {backward!r}; use fused|split")
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
              interpret=interpret, out_dtype=out_dtype)
    if backward == "fused":
        return flash_dqdkv(q, k, v, do, lse, delta, **kw)
    dq = flash_dq(q, k, v, do, lse, delta, **kw)
    dk, dv = flash_dkv(q, k, v, do, lse, delta, **kw)
    return dq, dk, dv


def _flash_bhsd_bwd(scale, causal, block_q, block_k, interpret, backward,
                    res, do):
    q, k, v, o, lse = res
    return flash_backward(q, k, v, o, do, lse, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, backward=backward)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def _fit_block(s: int, want: int | None) -> int:
    """Largest divisor of ``s`` ≤ ``want`` that is a multiple of 8; ``None``
    picks a size by S.

    Measured on v5e (in-jit delta timing, flagship [2, S, 16, 128]):
    fatter tiles win decisively at long S — at S=4096, 1024×1024 blocks
    run the causal forward 2.0× faster than 512×512 (1.74 vs 3.41 ms,
    0.40 vs 0.21 MXU fraction) and the backward 1.4× (3.64 vs 5.17 ms);
    at S=2048 the 512×1024 shape wins; 2048-blocks fail to compile
    (VMEM). The None default is therefore ``min(1024, max(128, S/4))``
    — the q-block rule; ``flash_attention`` widens the K default to
    ``S/2`` (K tiles amortise across the q sweep). Candidates step down
    in units of 8 (the f32 sublane) so a non-tileable divisor like 125
    (S=250) — which compiles under CPU interpret but real-TPU pallas
    rejects or badly pads — can never be picked; sequences with no
    8-multiple divisor get the ValueError path in ``flash_attention``
    ("pad the sequence") instead.
    """
    if want is None:
        want = min(1024, max(128, s // 4))
    if s <= 8:
        return s  # tiny test shapes; interpret mode only
    b = min(want - want % 8, s - s % 8)
    while b >= 8 and s % b:
        b -= 8
    return b if b >= 8 else 0


def flash_attention(q, k, v, *, causal: bool = True, scale: float | None = None,
                    block_q: int | None = None, block_k: int | None = None,
                    interpret: bool | None = None,
                    backward: str = "fused"):
    """Fused flash attention on ``[B, S, H, D]`` inputs (burn-in layout).

    Blocks default to a measured size heuristic and shrink to the largest
    divisor of S ≤ the requested size, so any sequence length works; sizes
    that leave no MXU-tileable divisor (< 8 for an S > 8) are rejected.
    ``backward`` selects the VJP kernels: ``"fused"`` (default; one
    single-pass pallas kernel, P/dS once per tile) or ``"split"`` (the
    historical dq + dkv two-kernel path, kept for A/B timing and the
    differential-correctness oracle). Returns ``[B, S, H, D]`` in the
    input dtype.
    """
    b, s, h, d = q.shape
    if backward not in ("fused", "split"):
        raise ValueError(
            f"unknown backward impl {backward!r}; use fused|split")
    if block_k is None:
        # K blocks default wider than q blocks (S/2 vs S/4, cap 1024):
        # each K tile is DMA'd once per q-block sweep, so fatter K tiles
        # amortise better — measured best at S=2048 (512×1024) and tied
        # at S=4096 (1024×1024); see _fit_block
        block_k = min(1024, max(128, s // 2))
    block_q, block_k = _fit_block(s, block_q), _fit_block(s, block_k)
    if s > 8 and (block_q < 8 or block_k < 8):
        raise ValueError(
            f"seq len {s} has no block divisor in [8, 128]; pad the sequence")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _on_interpret_platform()
    if not interpret and (block_q % 8 or block_k % 8):
        # tiny s <= 8 shapes pass _fit_block for interpret-mode tests, but
        # real-TPU mosaic rejects sub-sublane blocks — fail with the
        # actionable error instead of a raw compile failure
        raise ValueError(
            f"blocks ({block_q}, {block_k}) are not 8-multiples; real-TPU "
            f"pallas needs sublane-aligned blocks — pad the sequence")

    def to_bhsd(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    o = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), scale, causal,
                    block_q, block_k, interpret, backward)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def pick_impl(impl: str | None, seq_len: int, what: str) -> str:
    """Shared flash/dense tile-math selection for the sharded attention
    wrappers (ring, Ulysses). ``impl=None`` picks "flash" when ``seq_len``
    (the length the LOCAL attention problem runs at) tiles into 8-multiple
    blocks, "dense" otherwise — so shapes that worked pre-flash keep
    working; an explicit impl is validated and passed through."""
    if impl not in (None, "dense", "flash"):
        raise ValueError(f"unknown {what} impl {impl!r}; use dense|flash")
    if impl is not None:
        return impl
    return "flash" if (seq_len <= 8 and _on_interpret_platform()) or \
        _fit_block(seq_len, None) >= 8 else "dense"
