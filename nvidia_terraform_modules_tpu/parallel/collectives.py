"""Collective micro-probes: correctness + achieved ICI bandwidth.

These are the executable replacement for the reference's manual "is the fabric
up" checks (node-to-node SG rules at ``/root/reference/eks/main.tf:28-49`` plus
README runbooks). Each probe returns (ok, seconds, bytes_moved) so callers can
derive achieved bandwidth. All are built on ``shard_map`` so they compile to
bare XLA collectives over the mesh — no NCCL analogue, the compiler owns the
schedule.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

shard_map = jax.shard_map

from ..utils.timing import median_time


def _axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def psum_probe(mesh: Mesh, axis: str = "dp", n_elems: int = 1 << 20) -> dict[str, Any]:
    """All-reduce over ``axis``; verifies the sum matches the axis size.

    Each shard contributes a vector of ones, so the psum result must equal the
    number of participants — the same invariant the north-star smoke test
    asserts in-cluster.
    """
    n_dev = _axis_size(mesh, axis)
    spec = P(axis)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
    def allreduce(x):
        return jax.lax.psum(x, axis)

    x = jnp.ones((n_dev * n_elems,), dtype=jnp.float32)
    out = jax.device_get(allreduce(x))
    ok = bool(np.allclose(out, float(n_dev)))
    secs = median_time(allreduce, x)
    # ring all-reduce moves 2*(n-1)/n of the full buffer per chip
    moved = 2 * (n_dev - 1) / n_dev * x.nbytes
    return {"ok": ok, "seconds": secs, "bytes": moved, "participants": n_dev}


def all_gather_probe(mesh: Mesh, axis: str = "tp", n_elems: int = 1 << 18) -> dict[str, Any]:
    """All-gather over ``axis``; verifies every shard sees every contribution."""
    n_dev = _axis_size(mesh, axis)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
    )
    def gather(x):
        g = jax.lax.all_gather(x, axis, tiled=True)
        # collapse so out_specs stays sharded; content check happens on host
        return g

    x = jnp.tile(jnp.arange(n_dev, dtype=jnp.float32), (n_elems,)).reshape(-1)
    x = jnp.sort(x)  # shard i holds value i everywhere
    out = jax.device_get(gather(x))
    ok = bool(np.unique(out).size == n_dev)
    secs = median_time(gather, x)
    moved = (n_dev - 1) / n_dev * (x.nbytes * n_dev)
    return {"ok": ok, "seconds": secs, "bytes": moved, "participants": n_dev}


def reduce_scatter_probe(mesh: Mesh, axis: str = "tp", n_elems: int = 1 << 18) -> dict[str, Any]:
    """psum_scatter over ``axis`` — the backbone of row-parallel matmuls."""
    n_dev = _axis_size(mesh, axis)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    def rscatter(x):
        return jax.lax.psum_scatter(x, axis, tiled=True)

    x = jnp.ones((n_dev * n_dev * n_elems,), dtype=jnp.float32)
    out = jax.device_get(rscatter(x))
    ok = bool(np.allclose(out, float(n_dev)))
    secs = median_time(rscatter, x)
    moved = (n_dev - 1) / n_dev * x.nbytes
    return {"ok": ok, "seconds": secs, "bytes": moved, "participants": n_dev}


def ring_permute_probe(mesh: Mesh, axis: str = "sp", n_elems: int = 1 << 18) -> dict[str, Any]:
    """One hop of a ring ``ppermute`` — the primitive under ring attention.

    Long-context sequence parallelism (ring attention) is a chain of these
    neighbour exchanges; a working ring hop on every axis position proves the
    ICI ring the ``gke-tpu`` placement policy promised actually exists.
    """
    n_dev = _axis_size(mesh, axis)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    def ring_hop(x):
        idx = jax.lax.axis_index(axis).astype(jnp.float32)
        payload = x + idx
        return jax.lax.ppermute(payload, axis, perm)

    x = jnp.zeros((n_dev * n_elems,), dtype=jnp.float32)
    out = jax.device_get(ring_hop(x)).reshape(n_dev, n_elems)
    expected = (np.arange(n_dev, dtype=np.float32) - 1) % n_dev
    ok = bool(np.allclose(out, expected[:, None]))
    secs = median_time(ring_hop, x)
    moved = x.nbytes  # every chip sends its full shard one hop
    return {"ok": ok, "seconds": secs, "bytes": moved, "participants": n_dev}


ALL_PROBES = {
    "psum": psum_probe,
    "all_gather": all_gather_probe,
    "reduce_scatter": reduce_scatter_probe,
    "ring_permute": ring_permute_probe,
}
