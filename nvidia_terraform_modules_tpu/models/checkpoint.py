# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Checkpoint/resume for the burn-in workload (orbax, sharded, multi-host).

Why this exists: the ``gke-tpu`` module makes *preemptible* TPU slices a
first-class provisioning option (``gke-tpu/tpu_slices.tf`` ``spot`` flag —
the TPU analogue of the reference's preemptible GPU pools,
``/root/reference/gke/variables.tf:65-68``). A spot slice can vanish
mid-burn-in; Kubernetes restarts the Job pod, and the validation workload
must *resume* rather than start over — otherwise burn-in time on flaky
capacity is unbounded. The reference has no workload at all, so its
checkpoint story is terraform state only (SURVEY §5); ours covers the
training side with orbax, the TPU-idiomatic checkpointer:

- **sharded**: saves/restores ``jax.Array``\\ s with their ``NamedSharding``
  preserved — each host writes only its shards (no gather through one host,
  no HBM blow-up), restore places shards directly on the mesh;
- **atomic + retained**: orbax commits a step directory atomically, so a
  pod killed mid-save leaves the previous step restorable; ``max_to_keep``
  bounds disk;
- **step-numbered**: the Job's global step survives restarts — a resumed
  attempt continues the counter (and the params) from the last committed
  checkpoint instead of resetting to zero, so the step count in the JSON
  verdict reflects cumulative training across preemptions;
- **run-scoped**: a *successful* run calls :meth:`Checkpointer.clear`, so a
  later fresh Job (a new ``terraform apply``) starts at step 0 instead of
  accumulating steps across unrelated runs.

``directory`` may be a local path or a remote URI (``gs://...`` — orbax's
tensorstore backend); remote URIs pass through untouched, local paths are
absolutised for orbax.
"""

from __future__ import annotations

import os
from typing import Any

import jax

from .burnin import BurnInConfig, init_params, param_shardings


def _is_remote(directory: str) -> bool:
    return "://" in directory


def _root(directory: str) -> str:
    # os.path.abspath would mangle gs://bucket/x into <cwd>/gs:/bucket/x
    return directory if _is_remote(directory) else os.path.abspath(directory)


def _no_checkpoint_possible(directory: str) -> bool:
    """Cheap local fast-path; never touches (or creates) remote storage
    when the directory plainly doesn't exist yet."""
    return not _is_remote(directory) and not os.path.isdir(directory)


class Checkpointer:
    """One orbax ``CheckpointManager`` for a whole run.

    The run loop saves every step; constructing a fresh manager per save
    would re-list the checkpoint directory (a remote prefix listing per
    step on ``gs://``) and re-run retention from scratch each time. One
    instance amortises that; use as a context manager or call
    :meth:`close`.
    """

    def __init__(self, directory: str, max_to_keep: int = 2,
                 async_save: bool = False):
        """``async_save=True`` makes :meth:`save` return after the device
        arrays are snapshotted, with serialization/commit running behind
        the next training steps — the standard TPU lever for hiding
        checkpoint I/O (orbax writes from a host copy, so training may
        mutate params immediately). The commit point moves to
        :meth:`flush` / :meth:`close` / the next ``save`` (orbax
        serializes overlapping saves). The smoke-test Job keeps the
        blocking default: it may be preempted right after a step, and an
        uncommitted async write racing pod teardown would lose the step.
        """
        self.directory = directory
        self._max_to_keep = max_to_keep
        self._async = async_save
        self._mgr = None

    def _manager(self):
        if self._mgr is None:
            import orbax.checkpoint as ocp

            self._mgr = ocp.CheckpointManager(
                _root(self.directory),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self._max_to_keep, create=True),
            )
        return self._mgr

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._mgr is not None:
            # commit any in-flight async save before tearing down — a
            # close that dropped a scheduled write would silently lose
            # the run's last step
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None

    def flush(self) -> None:
        """Block until every scheduled (async) save has committed."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        if _no_checkpoint_possible(self.directory):
            return None
        # reads must not observe a scheduled-but-uncommitted async step
        # (the manager's cache lists it before the commit lands)
        self.flush()
        return self._manager().latest_step()

    def save(self, step: int, params: Any,
             meta: dict[str, Any] | None = None) -> None:
        """Atomic save of ``params`` (+ JSON ``meta``).

        Blocking by default (the smoke-test Job may be preempted right
        after a step, and an uncommitted write racing pod teardown would
        lose the commit); with ``async_save=True`` the commit overlaps
        subsequent compute and lands at the next save/:meth:`flush`/
        :meth:`close`.
        """
        import orbax.checkpoint as ocp

        mgr = self._manager()
        mgr.save(step, args=ocp.args.Composite(
            params=ocp.args.StandardSave(params),
            meta=ocp.args.JsonSave(meta or {}),
        ))
        if not self._async:
            mgr.wait_until_finished()

    def restore(self, cfg: BurnInConfig, rules=None,
                step: int | None = None,
                ) -> tuple[Any, int, dict[str, Any]] | None:
        """Restore ``(params, step, meta)`` from the latest (or given) step.

        Params come back placed: an abstract pytree built from ``cfg``
        (and the mesh's sharding rules, when given) tells orbax the target
        shape/dtype/sharding of every leaf, so restore writes device
        shards directly — the resume path costs one HBM-resident copy,
        same as init. Returns None when no checkpoint exists.
        """
        abstract = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg))
        if rules is not None:
            shardings = param_shardings(abstract, rules)
            abstract = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(
                    a.shape, a.dtype, sharding=s),
                abstract, shardings)
        return self.restore_tree(abstract, step)

    def restore_tree(self, abstract: Any, step: int | None = None,
                     ) -> tuple[Any, int, dict[str, Any]] | None:
        """Restore an arbitrary pytree saved with :meth:`save`.

        ``abstract`` is a ``jax.ShapeDtypeStruct`` pytree (shardings
        included) describing the target placement — the generalisation of
        :meth:`restore` for trees that aren't bare burn-in params, e.g. the
        AdamW train state ``{"params": …, "opt": …}`` whose moments carry
        ZeRO-1 shardings (``models/optimizer.py``). Returns
        ``(tree, step, meta)`` or None when no checkpoint exists.
        """
        import orbax.checkpoint as ocp

        if _no_checkpoint_possible(self.directory):
            return None
        self.flush()   # never restore a step whose commit hasn't landed
        mgr = self._manager()
        if step is None:
            step = mgr.latest_step()
        if step is None:
            return None
        restored = mgr.restore(step, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(abstract),
            meta=ocp.args.JsonRestore(),
        ))
        return restored["params"], step, dict(restored["meta"] or {})

    def clear(self) -> int:
        """Delete every committed step; returns how many were removed.

        Called after a run *succeeds*: the burn-in is validated, resume
        state is no longer needed, and leaving it behind would make the
        next fresh Job silently continue a finished run's step count.

        Multi-host discipline: ``mgr.delete`` is collective (it contains a
        global-process barrier), so every process must issue the same
        delete sequence. Each process snapshots the step list, then a
        barrier ensures all snapshots happened *before* any deletion
        mutates the shared directory — without it, a process listing late
        would see fewer steps, skip a delete, and leave its peers hanging
        in orbax's barrier until the coordination timeout.
        """
        if _no_checkpoint_possible(self.directory):
            return 0
        # an uncommitted async save racing the delete could re-land its
        # step AFTER the directory sweep — commit everything first
        self.flush()
        mgr = self._manager()
        steps = list(mgr.all_steps())
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("checkpointer_clear_snapshot")
        for s in steps:
            mgr.delete(s)
        return len(steps)


# One-shot convenience wrappers (tests, ad-hoc use). Run loops should hold
# a Checkpointer instead of paying manager construction per call.

def latest_step(directory: str) -> int | None:
    """Highest committed step in ``directory``, or None if no checkpoint."""
    with Checkpointer(directory) as c:
        return c.latest_step()


def save_checkpoint(directory: str, step: int, params: Any,
                    meta: dict[str, Any] | None = None,
                    max_to_keep: int = 2) -> None:
    with Checkpointer(directory, max_to_keep) as c:
        c.save(step, params, meta)


def restore_checkpoint(
    directory: str,
    cfg: BurnInConfig,
    rules=None,
    step: int | None = None,
) -> tuple[Any, int, dict[str, Any]] | None:
    with Checkpointer(directory) as c:
        return c.restore(cfg, rules, step)


def clear_checkpoints(directory: str) -> int:
    with Checkpointer(directory) as c:
        return c.clear()
