# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Host-RAM block tier for the paged KV cache — the spill side of the
tiered prefix index.

``prefix_keep_blocks`` caps what the :class:`..paging.PrefixIndex` may
retain at what the HBM pool spares, so the serve engine's prefix hit
fraction is bounded by device memory even though a fleet's Zipf-head
template working set is host-sized, not HBM-sized (the TPU-serving
comparison papers make host↔HBM staging the decisive serving lever on
TPU hosts — a v5e host carries 48-384 GB of RAM next to 16 GB of HBM
per chip). This module is the second tier: a pinned host-side block
pool (:class:`HostBlockPool`) the index SPILLS evicted chains into
instead of dropping them, and swaps back in on a later prefix hit.

Division of labour mirrors the device pool exactly:

- the **pool** owns bytes — numpy-backed ``[host_blocks, block_size,
  kv, D]`` arrays per layer (int8 scale sidecars ride along), one
  free-list allocator (:class:`..paging.BlockAllocator` at refcount 1 —
  a host block has exactly one owner, its index entry);
- the **index** owns which chain holds which host block (the
  ``tier="host"`` entries in ``PrefixIndex``);
- the **engine** owns the swap schedule — when a prefix hit lands on a
  spilled chain, admission allocates fresh device blocks and imports
  the host rows through ``paging.import_block_rows``, double-buffered
  against the wave loop via :meth:`HostBlockPool.stage`.

Integrity is the checkpoint engine's crc discipline applied to the
block transfer wire format: every spilled block is stamped with
``paging.transfer_crc`` over its single-block payload at store time and
re-verified at load — RAM is not ECC-trustworthy at fleet scale, a bad
row silently decoded into a popular template would corrupt EVERY
request that hits it, so a mismatch raises the CLASSIFIED
:class:`HostSpillCorruptError` (the engine drops the chain and
prefills from tokens — slow, never wrong), exactly like a corrupt
checkpoint record quarantines instead of restoring.

``tests/test_paging.py`` pins the spill→swap-in roundtrip bitwise per
cache dtype, the corruption path, and the exhaustion fallback;
``tests/test_serving.py`` the engine-level bit-match (spill on == spill
off) across the scheduler-lever matrix.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Any, Sequence

import numpy as np

from .burnin import BurnInConfig
from .paging import BlockAllocator, transfer_crc


class HostSpillCorruptError(RuntimeError):
    """A spilled block's bytes no longer match their store-time crc —
    a CLASSIFIED integrity failure (like ``CorruptCheckpointError``):
    the caller must drop the chain and recompute from tokens, never
    decode from the corrupt rows."""


class HostBlockPool:
    """Pinned host-side block pool: the spill target behind the prefix
    index.

    Layout matches the device pool's transferable keys exactly —
    per-layer ``k``/``v`` ``[host_blocks, block_size, kv, D]`` numpy
    arrays (plus ``k_scale``/``v_scale`` ``[host_blocks, block_size,
    kv]`` float32 sidecars for int8 caches) — so a spill is
    ``paging.export_block_rows`` landing in host rows and a swap-in is
    the same payload handed back to ``paging.import_block_rows``: the
    round trip is memcpy-bitwise per dtype, never a re-quantisation.

    Each stored block is crc-stamped (``paging.transfer_crc`` over its
    single-block payload) and verified at :meth:`load`/:meth:`stage`;
    a mismatch raises :class:`HostSpillCorruptError` loudly.

    :meth:`store` is all-or-nothing like the device allocator: host
    exhaustion returns ``None`` and the caller falls back to a plain
    drop (a lost retained prefix costs a re-prefill, never
    correctness). :meth:`stage` is the async half of the engine's
    double-buffered swap-in: it snapshots and verifies the rows NOW
    (so a later free/reuse of the host block cannot race the reader)
    and moves the host→device transfer onto a worker thread, so the
    wave loop's decode dispatch overlaps the next admission's swap-in.
    """

    def __init__(self, cfg: BurnInConfig, host_blocks: int, *,
                 block_size: int, cache_dtype: str = "bf16"):
        if host_blocks < 1:
            raise ValueError(
                f"host_blocks must be >= 1, got {host_blocks}")
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        if cache_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"unknown cache_dtype {cache_dtype!r}: use bf16|int8")
        self.host_blocks = host_blocks
        self.block_size = block_size
        self.cache_dtype = cache_dtype
        quant = cache_dtype == "int8"
        kv_shape = (host_blocks, block_size, cfg.kv_heads, cfg.head_dim)
        buf_dtype = np.dtype("int8") if quant else np.dtype(cfg.dtype)
        self._bufs: dict[str, list[np.ndarray]] = {
            "k": [np.zeros(kv_shape, buf_dtype)
                  for _ in range(cfg.n_layers)],
            "v": [np.zeros(kv_shape, buf_dtype)
                  for _ in range(cfg.n_layers)],
        }
        if quant:
            self._bufs["k_scale"] = [
                np.zeros(kv_shape[:3], np.float32)
                for _ in range(cfg.n_layers)]
            self._bufs["v_scale"] = [
                np.zeros(kv_shape[:3], np.float32)
                for _ in range(cfg.n_layers)]
        # reserved=0: there is no garbage block on the host side — no
        # device writes ever target these rows, so every id is real
        self._alloc = BlockAllocator(host_blocks, reserved=0)
        self._crc: dict[int, int] = {}
        self._pool: Any = None          # lazy ThreadPoolExecutor
        self.stored_blocks = 0          # cumulative spill traffic
        self.loaded_blocks = 0

    def reset(self) -> None:
        """Fresh run over the SAME buffers: new allocator, cleared crc
        stamps, zeroed traffic counters. The engine builds the pool
        ONCE at ``make_serve_engine`` time (the big numpy allocation
        happens at build, not mid-serving) and resets it per run —
        rows need no re-zeroing, a block is only readable once a new
        store stamps it."""
        self._alloc = BlockAllocator(self.host_blocks, reserved=0)
        self._crc.clear()
        self.stored_blocks = 0
        self.loaded_blocks = 0

    # ------------------------------------------------------- accounting

    @property
    def in_use(self) -> int:
        return self._alloc.in_use

    @property
    def free_blocks(self) -> int:
        return self._alloc.free_blocks

    @property
    def high_water(self) -> int:
        return self._alloc.high_water

    def stats(self) -> dict[str, int]:
        return {
            "host_blocks": self.host_blocks,
            "in_use": self.in_use,
            "free": self.free_blocks,
            "high_water": self.high_water,
            "stored_blocks": self.stored_blocks,
            "loaded_blocks": self.loaded_blocks,
        }

    # ------------------------------------------------------- store side

    def _block_payload(self, hid: int) -> dict[str, list[np.ndarray]]:
        """The single-block payload view of host block ``hid`` — the
        same wire format ``export_block_rows`` produces, so one crc
        definition (``paging.transfer_crc``) covers both sides."""
        return {k: [buf[hid:hid + 1] for buf in bufs]
                for k, bufs in self._bufs.items()}

    def store(self, pool: dict, dev_blocks: Sequence[int]) -> list[int] | None:
        """Copy the physical content of ``dev_blocks`` out of the
        device ``pool`` into host rows: returns the host block ids (one
        per device block, in order), or ``None`` when the host pool
        cannot hold them all (all-or-nothing — the caller drops the
        chain instead). Each row is crc-stamped at store time."""
        from .paging import export_block_rows, pool_transfer_keys

        dev_blocks = list(dev_blocks)
        if not dev_blocks:
            return []
        keys = pool_transfer_keys(pool)
        if sorted(keys) != sorted(self._bufs):
            raise ValueError(
                f"device pool carries keys {sorted(keys)}, host pool "
                f"was built for {sorted(self._bufs)} (cache_dtype "
                f"mismatch between the tiers?)")
        if self.free_blocks < len(dev_blocks):
            # capacity check BEFORE the device→host readback: this
            # runs inside trim()/reclaim() on the wave loop, and a
            # full pool must refuse the spill with zero device
            # traffic (alloc is all-or-nothing, so this is exact)
            return None
        return self.adopt(export_block_rows(pool, dev_blocks))

    def adopt(self, payload: dict) -> list[int] | None:
        """Store an already-exported wire payload (numpy or device
        arrays in ``export_block_rows``'s format, ``n`` blocks per
        buffer) into host rows — the direct-ingest half :meth:`store`
        routes through, and the door the fleet's warm-bring-up
        migration uses (a chain published by one replica adopts into
        another replica's pool, or into the fleet-shared
        :class:`WarmChainStore`, without ever touching a device pool).
        All-or-nothing like :meth:`store`; rows crc-stamp at adopt
        time."""
        if sorted(payload) != sorted(self._bufs):
            raise ValueError(
                f"payload carries keys {sorted(payload)}, host pool "
                f"was built for {sorted(self._bufs)} (cache_dtype "
                f"mismatch between the tiers?)")
        n = int(np.asarray(payload["k"][0]).shape[0])
        if n == 0:
            return []
        hids = self._alloc.alloc(n)
        if hids is None:
            return None
        # one readback for the whole chain (the spill's device→host
        # hop), then ONE fancy-index write per (key, layer) — this
        # runs inside trim()/reclaim() on the wave loop, so the copy
        # must be vectorised, not a per-row Python loop
        idx = np.asarray(hids)
        for k in self._bufs:
            for buf, src in zip(self._bufs[k], payload[k]):
                buf[idx] = np.asarray(src)
        for hid in hids:
            self._crc[hid] = transfer_crc(self._block_payload(hid))
        self.stored_blocks += len(hids)
        return hids

    def free(self, host_ids: Sequence[int]) -> None:
        for hid in host_ids:
            self._crc.pop(int(hid), None)
        self._alloc.free(list(host_ids))

    # -------------------------------------------------------- load side

    def _verify(self, hid: int) -> None:
        want = self._crc.get(hid)
        if want is None:
            raise ValueError(
                f"host block {hid} holds no spilled content — foreign "
                f"or already-freed id")
        got = transfer_crc(self._block_payload(hid))
        if got != want:
            raise HostSpillCorruptError(
                f"host block {hid} failed its crc re-check "
                f"(stored {want:#010x}, read {got:#010x}) — host RAM "
                f"corruption; drop the chain and prefill from tokens, "
                f"never decode these rows")

    def load(self, host_ids: Sequence[int]) -> dict[str, list[np.ndarray]]:
        """The swap-in payload for ``host_ids``: crc-verified rows in
        ``export_block_rows``'s wire format, ready for
        ``paging.import_block_rows`` into freshly granted device
        blocks. Raises :class:`HostSpillCorruptError` on a bad row."""
        hids = [int(h) for h in host_ids]
        for hid in hids:
            self._verify(hid)
        self.loaded_blocks += len(hids)
        return {k: [np.stack([buf[h] for h in hids])
                    for buf in bufs]
                for k, bufs in self._bufs.items()}

    def stage(self, host_ids: Sequence[int]):
        """The ASYNC half of the double-buffered swap-in: snapshot and
        crc-verify the rows now (immune to a later free/overwrite of
        the host block), then push the host→device transfer onto the
        worker thread so it overlaps the wave loop's decode dispatch.
        Returns a future whose ``result()`` is a device-resident
        payload for ``import_block_rows``; a crc failure raises
        :class:`HostSpillCorruptError` from the snapshot, before any
        thread is involved."""
        from concurrent.futures import ThreadPoolExecutor

        payload = self.load(host_ids)            # snapshot + verify NOW
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="hostkv-swap")

        def to_device():
            import jax

            return {k: [jax.device_put(b) for b in bufs]
                    for k, bufs in payload.items()}

        return self._pool.submit(to_device)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None


class WarmChainStore:
    """FLEET-SHARED host tier for warm replica bring-up: chain-keyed
    prefix chains in one :class:`HostBlockPool`, published by replicas
    at drain/close time and taken by joining replicas at spawn time
    (the elastic fleet's state-migration transport, ``models/fleet.py``).

    The per-replica spill tier answers "my HBM cap is smaller than my
    working set"; this store answers "a replica that did not exist a
    second ago should not cold-start": a draining (scaled-down) replica
    publishes its retained prefix chains here (``PrefixIndex.
    export_chains`` → :meth:`publish`), and a scale-up's bring-up takes
    the chains whose ROOT key the post-join ring assigns to the joiner
    (:meth:`take`) and seeds them host-side into the fresh replica's
    index (``PrefixIndex.seed_host``) — so the Zipf-head template
    working set survives replica churn instead of re-prefilling from
    tokens on every join.

    Chains are filed by their LEAF chain key (``paging.chain_key``) and
    kept LRU, but rows are stored PER CHAIN NODE with refcounts —
    chains sharing a template prefix share its rows, so a popular
    template with many divergent suffixes costs its node count, never
    node-count × leaf-count. Every row rides the pool's crc
    discipline, so a take re-verifies at load and a corrupt chain is
    DROPPED loudly (billed, never migrated). Thread-safe: replicas
    publish from their run threads, the router takes from its monitor
    thread. A take COPIES — the store keeps its rows, so any number
    of joiners can inherit the same head."""

    def __init__(self, cfg: BurnInConfig, host_blocks: int, *,
                 block_size: int, cache_dtype: str = "bf16"):
        import threading

        self.pool = HostBlockPool(cfg, host_blocks,
                                  block_size=block_size,
                                  cache_dtype=cache_dtype)
        self._lock = threading.Lock()
        # leaf chain key → chunks tuple, LRU order; rows are filed
        # PER CHAIN NODE (``_rows``: node chain key → [host id,
        # refcount]) so chains sharing a template prefix share its
        # rows — a Zipf-head template with L divergent suffix leaves
        # costs ~B+L rows, never B×L (the blow-up would evict other
        # templates' heads exactly when templates are popular)
        self._chains: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._rows: dict[bytes, list] = {}
        self.published_chains = 0       # chains newly stored
        self.store_full_drops = 0       # publishes the full pool refused
        self.corrupt_dropped = 0        # takes that failed their crc
        self.taken_chains = 0           # chains handed to joiners

    def __len__(self) -> int:
        with self._lock:
            return len(self._chains)

    @staticmethod
    def _node_keys(chunks) -> list:
        from .paging import chain_key

        return [chain_key(chunks, k) for k in range(1, len(chunks) + 1)]

    def _drop_chain_locked(self, leaf) -> None:
        """Unfile one chain (lock held): decrement every node's ref,
        free rows no surviving chain references."""
        chunks = self._chains.pop(leaf)
        for nk in self._node_keys(chunks):
            row = self._rows[nk]
            row[1] -= 1
            if row[1] == 0:
                self.pool.free([row[0]])
                del self._rows[nk]

    def publish(self, chains: Sequence[tuple]) -> int:
        """Store ``(chunks, payload)`` chains (``payload`` in
        ``export_block_rows`` wire format covering the whole chain),
        given HOTTEST-first (``PrefixIndex.export_chains``' MRU
        order). A chain already filed under the same leaf key
        refreshes its LRU slot — content is identical by the key's
        construction, so re-storing would only burn pool rows. Under
        capacity pressure a chain evicts UNUSED LRU chains and is
        dropped (billed) if it still does not fit — publishing is
        best-effort by design, correctness never depends on it. The
        batch is INSERTED coldest-first so the OrderedDict's eviction
        front holds the cold tail and the popular head survives the
        squeeze (the retention promise the runbook makes); a chain
        bigger than the whole pool is refused up front, never allowed
        to evict everything and then fail anyway. Returns chains
        newly stored."""
        stored = 0
        with self._lock:
            for chunks, payload in reversed(list(chains)):
                chunks = tuple(tuple(c) for c in chunks)
                if not chunks:
                    continue
                node_keys = self._node_keys(chunks)
                leaf = node_keys[-1]
                if leaf in self._chains:
                    self._chains.move_to_end(leaf)
                    continue
                while True:
                    # recomputed per attempt: evicting an LRU chain
                    # may free a PREFIX node this chain shares, so the
                    # missing set is only valid until the next drop
                    missing = [i for i, nk in enumerate(node_keys)
                               if nk not in self._rows]
                    if len(missing) > self.pool.host_blocks:
                        hids = None          # bigger than the pool
                        break
                    if not missing:
                        hids = []            # fully shared already
                        break
                    sliced = {k: [np.asarray(b)[missing] for b in bufs]
                              for k, bufs in payload.items()}
                    hids = self.pool.adopt(sliced)
                    if hids is not None or not self._chains:
                        break
                    self._drop_chain_locked(next(iter(self._chains)))
                if hids is None:
                    self.store_full_drops += 1
                    continue
                for i, hid in zip(missing, hids):
                    self._rows[node_keys[i]] = [int(hid), 0]
                for nk in node_keys:
                    self._rows[nk][1] += 1
                self._chains[leaf] = chunks
                self.published_chains += 1
                stored += 1
        return stored

    def take(self, owns) -> list[tuple[tuple, dict]]:
        """The joiner's share: every stored chain whose ROOT key
        satisfies ``owns(root_key)`` (the router passes the post-join
        ring's assignment), as ``(chunks, payload)`` records ready for
        ``HostBlockPool.adopt`` + ``PrefixIndex.seed_host`` on the
        joining replica. Rows are crc-verified at load; a corrupt
        chain is discarded from the store and billed, never handed
        out. Chains are returned sorted by key (publish order is
        thread-timing; the joiner's seeding order must not be) and
        stay in the store — takes copy."""
        out: list[tuple[tuple, dict]] = []
        with self._lock:
            for key in sorted(self._chains):
                chunks = self._chains[key]
                node_keys = self._node_keys(chunks)
                if not owns(node_keys[0]):
                    continue
                hids = [self._rows[nk][0] for nk in node_keys]
                try:
                    payload = self.pool.load(hids)
                except HostSpillCorruptError:
                    self._drop_chain_locked(key)
                    self.corrupt_dropped += 1
                    continue
                self._chains.move_to_end(key)
                out.append((chunks, payload))
                self.taken_chains += 1
        return out

    def clear(self) -> None:
        with self._lock:
            while self._chains:
                self._drop_chain_locked(next(iter(self._chains)))

    def stats(self) -> dict:
        with self._lock:
            return {
                "chains": len(self._chains),
                "blocks_in_use": self.pool.in_use,
                "host_blocks": self.pool.host_blocks,
                "published_chains": self.published_chains,
                "taken_chains": self.taken_chains,
                "store_full_drops": self.store_full_drops,
                "corrupt_dropped": self.corrupt_dropped,
            }


class IndexSpill:
    """The duck-typed spill adapter ``PrefixIndex`` drives: binds a
    :class:`HostBlockPool` to the engine's LIVE device pool reference
    (the wave loop rebinds ``pool`` every dispatch, so the adapter
    reads it through a callable, never a snapshot). Kept tiny on
    purpose — ``paging.py`` stays importable without this module, the
    index only sees ``store(dev_blocks) → host_ids|None`` and
    ``free(host_ids)``."""

    def __init__(self, host: HostBlockPool, pool_ref):
        self.host = host
        self._pool_ref = pool_ref

    def store(self, dev_blocks: Sequence[int]) -> list[int] | None:
        return self.host.store(self._pool_ref(), dev_blocks)

    def free(self, host_ids: Sequence[int]) -> None:
        self.host.free(host_ids)


class SnapshotCorruptError(RuntimeError):
    """A streamed param leaf's bytes no longer match their
    snapshot-time crc — a CLASSIFIED integrity failure (the
    :class:`HostSpillCorruptError` discipline applied to donor
    weights): the joiner must refuse the tree and re-request the
    stream, never build an engine on silently corrupt weights."""


class HostParamSnapshot:
    """Fleet-shared donor weights: ONE host-side contiguous numpy copy
    of the param tree with a per-leaf crc32, built once per fleet
    configure and streamed to every joiner.

    This generalises the pool's pinned-numpy + crc machinery beyond KV
    rows (ROADMAP item 4's weight-streaming half): the snapshot is the
    IMMUTABLE donor the multi-process transport pickles ONCE into a
    wire buffer (``MultiProcTransport._param_wire``) — N scale-ups
    used to ``device_get`` + re-pickle the full weight tree per child;
    now they frame the identical shared bytes per joiner — and
    :meth:`decode` re-verifies every leaf on the receiving side before
    the engine is built (RAM and wire are not ECC-trustworthy at fleet
    scale; a flipped weight bit would skew EVERY request the replica
    serves). Leaf order is ``jax.tree.leaves`` order, which both sides
    share by construction (quantised ``QTensor`` leaves flatten into
    their array fields on both sides identically).

    ``tests/test_aotcache.py`` pins the roundtrip bitwise, the per-leaf
    corruption classification, and the pickle-once sharing;
    ``tests/test_transport.py``'s chaos gates cover the respawn path a
    corrupt stream triggers."""

    def __init__(self, params):
        import jax

        self.tree = jax.tree.map(np.ascontiguousarray,
                                 jax.device_get(params))
        leaves = jax.tree.leaves(self.tree)
        self.crcs = [zlib.crc32(x.tobytes()) & 0xFFFFFFFF
                     for x in leaves]
        self.nbytes = int(sum(x.nbytes for x in leaves))

    def encode(self) -> dict:
        """The wire form (host arrays ride as-is — pickling is the
        transport's job, and doing it once is the point)."""
        return {"tree": self.tree, "crcs": list(self.crcs),
                "nbytes": self.nbytes}

    @staticmethod
    def decode(wire: dict):
        """Verify every leaf crc and return the param tree; a mismatch
        (or a leaf-count drift) raises :class:`SnapshotCorruptError` —
        classified, never a silent decode."""
        import jax

        leaves = jax.tree.leaves(wire["tree"])
        crcs = wire["crcs"]
        if len(leaves) != len(crcs):
            raise SnapshotCorruptError(
                f"snapshot carries {len(crcs)} leaf crcs for "
                f"{len(leaves)} leaves — foreign or truncated stream")
        for i, (leaf, crc) in enumerate(zip(leaves, crcs)):
            got = zlib.crc32(
                np.ascontiguousarray(leaf).tobytes()) & 0xFFFFFFFF
            if got != crc:
                raise SnapshotCorruptError(
                    f"param leaf {i}: crc {got:#010x} does not match "
                    f"snapshot crc {crc:#010x}")
        return wire["tree"]
