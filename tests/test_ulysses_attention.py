# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Ulysses (all-to-all) sequence parallelism: exactness, grads, burn-in.

The second long-context layout next to ring attention (SURVEY §5): one
all-to-all scatters heads / gathers sequence, local attention runs at full
sequence length, a mirror all-to-all restores the sharded layout. These tests
prove it produces the SAME numbers as dense attention — forward and backward —
on the mesh factorisations a v5e-8 slice supports.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    init_params,
    make_train_step,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.ops import (
    dense_reference_attention,
    ulysses_self_attention,
)
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh


def _mesh(jax, dp, sp, tp):
    devs = np.array(jax.devices()[: dp * sp * tp]).reshape(dp, sp, tp)
    return Mesh(devs, ("dp", "sp", "tp"))


def _qkv(b=4, s=16, h=8, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("dp,sp,tp", [(1, 1, 1), (1, 2, 1), (1, 8, 1),
                                      (2, 2, 2), (1, 2, 2), (4, 2, 1)])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(jax8, dp, sp, tp, causal):
    q, k, v = _qkv()
    ref = dense_reference_attention(q, k, v, causal=causal)
    out = ulysses_self_attention(q, k, v, _mesh(jax8, dp, sp, tp),
                                 causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


@pytest.mark.parametrize("impl", ["dense", "flash"])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_impls_match_dense_at_tile_scale(jax8, impl, causal):
    """Both local tile paths at shapes where flash actually tiles. Unlike
    the ring, the local problem runs at GLOBAL sequence length (s=256)."""
    q, k, v = _qkv(b=2, s=256, h=8, d=16)
    mesh = _mesh(jax8, 1, 4, 2)
    ref = dense_reference_attention(q, k, v, causal=causal)
    out = ulysses_self_attention(q, k, v, mesh, causal=causal, impl=impl)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


@pytest.mark.parametrize("impl", ["dense", "flash"])
def test_ulysses_gradients_match_dense(jax8, impl):
    q, k, v = _qkv(b=2, s=128, h=4, d=16)
    mesh = _mesh(jax8, 1, 4, 1)

    def f_uly(q, k, v):
        return jnp.sum(jnp.square(
            ulysses_self_attention(q, k, v, mesh, impl=impl)))

    def f_ref(q, k, v):
        return jnp.sum(jnp.square(dense_reference_attention(q, k, v)))

    g_uly = jax.grad(f_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        assert jnp.max(jnp.abs(a - b)) < 1e-3


def test_ulysses_invalid_impl_rejected(jax8):
    with pytest.raises(ValueError, match="unknown ulysses impl"):
        ulysses_self_attention(*_qkv(), _mesh(jax8, 1, 2, 1), impl="cuda")


def test_ulysses_head_divisibility_checked(jax8):
    """h=2 over sp=4: no valid head scatter — a clear error, not a crash."""
    q, k, v = _qkv(h=2)
    with pytest.raises(ValueError, match="divisible by sp"):
        ulysses_self_attention(q, k, v, _mesh(jax8, 1, 4, 1))


def test_ulysses_jit_under_sharded_inputs(jax8):
    """jit(shard_map) with committed sharded inputs — the production shape."""
    mesh = _mesh(jax8, 1, 4, 2)
    q, k, v = _qkv(s=32)
    spec = jax.sharding.NamedSharding(mesh, P("dp", "sp", "tp", None))
    q, k, v = (jax.device_put(t, spec) for t in (q, k, v))
    out = jax.jit(
        lambda q, k, v: ulysses_self_attention(q, k, v, mesh))(q, k, v)
    ref = dense_reference_attention(
        jax.device_get(q), jax.device_get(k), jax.device_get(v))
    assert jnp.max(jnp.abs(jax.device_get(out) - ref)) < 1e-5


def test_burnin_ulysses_matches_dense_forward(jax8):
    """attn="ulysses" must be a pure layout change: identical numbers."""
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    base = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                seq_len=16, batch=8, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_u = BurnInConfig(**base, attn="ulysses")
    params = init_params(jax.random.PRNGKey(0), cfg_d, rules)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d, rules)
    dense = forward(params, tokens, cfg_d, rules)
    uly = forward(params, tokens, cfg_u, rules)
    assert jnp.max(jnp.abs(dense - uly)) < 1e-5


def test_burnin_ulysses_train_step_decreases_loss(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, attn="ulysses")
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(8):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_ulysses_unsharded_config_falls_back_to_dense():
    """attn="ulysses" without rules (single chip) must still run."""
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=4, attn="ulysses")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg)
    logits = forward(params, tokens, cfg)
    assert logits.shape == (4, 16, 64)


def test_ulysses_pipelined_bitmatches_unpipelined(jax8):
    """Ulysses' post-all-to-all local attention runs the same pipelined
    flash kernels (PR 9): pipeline='on' must bit-match 'off' through the
    all-to-all sandwich (the default auto blocks give the global-S local
    problem an even K tiling either way)."""
    q, k, v = _qkv(b=2, s=256, h=8, d=16)
    mesh = _mesh(jax8, 1, 4, 2)

    def run(pipeline):
        return ulysses_self_attention(q, k, v, mesh, impl="flash",
                                      pipeline=pipeline)

    assert jnp.array_equal(run("on"), run("off"))
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(run("on") - ref)) < 2e-5
