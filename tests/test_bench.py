# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The bench capture's un-losable contract (round-2 VERDICT item 1).

The orchestrator is the artifact generator of record: whatever happens to
the backend or any metric section, `python bench.py` must exit 0 having
printed ONE parseable JSON line. These tests drive the real subprocess
machinery — section dispatch, timeout kill, error capture — and one full
end-to-end run on the CPU path.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(ROOT, "bench.py")


def _bench_mod():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_under_test", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cpu_env():
    # bench's OWN fallback env builder, so the tests can never drift from
    # the tunnel-env stripping the CPU path actually performs
    return _bench_mod()._cpu_env(dict(os.environ))


def test_run_section_reports_unknown_section():
    bench = _bench_mod()
    result, err = bench._run_section("nope", _cpu_env(), timeout=60,
                                     attempts=1)
    assert result is None
    assert "rc=2" in err


def test_run_section_timeout_kills_and_reports():
    """A hung section must burn only its own budget and come back as a
    timeout error — the failure mode that erased round 2's capture."""
    bench = _bench_mod()
    result, err = bench._run_section("devinfo", _cpu_env(), timeout=0.05,
                                     attempts=1)
    assert result is None
    assert "timeout" in err


def test_run_section_devinfo_roundtrip():
    bench = _bench_mod()
    result, err = bench._run_section("devinfo", _cpu_env(), timeout=120,
                                     attempts=1)
    assert err is None, err
    assert result["platform"] == "cpu" and result["devices"] >= 1


def test_section_registry_and_timeouts_agree():
    """Every section must carry a budget — a missing entry would KeyError
    mid-capture, exactly the un-losable contract's failure mode."""
    bench = _bench_mod()
    assert set(bench.SECTIONS) == set(bench.SECTION_TIMEOUT_S)


@pytest.mark.slow
def test_full_capture_emits_single_json_line_rc0():
    # the wrapper timeout must exceed the orchestrator's worst-case
    # section budgets (one hung section retried is ~20 min) — the
    # contract under test is that bench SURVIVES such a hang, so the
    # test must not TimeoutExpired first; the healthy path takes ~90 s
    proc = subprocess.run(
        [sys.executable, BENCH], env=_cpu_env(), cwd=ROOT,
        capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    payload = json.loads(lines[0])
    assert payload["metric"] == "accelerator_validation_seconds"
    assert payload["value"] > 0
    assert payload["bench_platform"] == "cpu"
    assert payload["smoke_ok"] is True
    for key in ("burnin_mfu", "decode_tokens_per_s",
                "decode_int8_tokens_per_s",
                "decode_int8_kvcache_tokens_per_s",
                "decode_moe_tokens_per_s", "decode_spec_tokens_per_s",
                "hbm_roofline", "flash_bwd_ms", "flash_bwd_fused_vs_split",
                "ckpt_save_ms", "ckpt_restore_ms",
                "ckpt_async_overlap_ratio",
                "telemetry_overhead_frac", "telemetry_export_ms"):
        assert key in payload, key
    # off-TPU the fused/split ratio measures the pallas interpreter, not
    # the kernels — the capture must say so next to the number
    assert "flash_bwd_fused_vs_split" in payload.get(
        "cpu_fallback_expectations", {})
    # likewise the checkpoint overlap ratio: tiny local-disk saves make
    # the hidden fraction a fixed-cost artifact off-chip
    assert "ckpt_async_overlap_ratio" in payload.get(
        "cpu_fallback_expectations", {})
    # and the telemetry overhead fraction: sub-ms CPU steps inflate the
    # fixed per-step record cost — the <2% gate lives in tier-1 on the
    # default CPU burn-in config, not in this tiny-shape capture
    assert "telemetry_overhead_frac" in payload.get(
        "cpu_fallback_expectations", {})
