# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Host-side input pipeline: streaming batches with device prefetch.

The burn-in workloads train on one fixed synthetic batch (right for a
validation Job: deterministic, zero I/O). Real training streams — and on
TPU the input pipeline's one job is to keep the host→device copy OFF the
step's critical path. The TPU-idiomatic recipe, implemented here:

- **host-side generation** in numpy (no jax ops → no device round-trips,
  no tracing): an infinite deterministic token stream per seed;
- **committed placement**: each batch is ``jax.device_put`` with the
  mesh's batch sharding (``P(data_axes)``), so the train step never
  reshuffles input — the same contract ``synthetic_batch`` satisfies;
- **prefetch depth N**: a sliding window of batches already in flight to
  the device. ``device_put`` is async (it returns before the copy lands),
  so issuing the NEXT batch's transfer before the step consumes the
  current one overlaps PCIe/DMA with MXU compute — the classic
  double-buffer, with no threads and no queues to tune.

The reference has no input pipeline at all (it is an IaC repo — SURVEY
§2); this is build-side substance for the framework's training story.
"""

from __future__ import annotations

import collections
from typing import Any, Iterator

import numpy as np

from .compat import pspec_axes


def token_stream(cfg, seed: int = 0,
                 bias: str = "zipf") -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Infinite deterministic LM batches ``(tokens, targets)`` on the host.

    Each batch is the next-token view of a fresh random stream — the
    streaming generalisation of ``models.synthetic_batch`` (one fixed
    batch), reproducible per ``seed``.

    ``bias="zipf"`` (default) draws tokens from a Zipf-shaped marginal
    (p ∝ 1/rank): unlike a uniform stream — whose optimal loss is exactly
    ``ln(vocab)``, leaving a fresh-data-each-step run nothing to learn —
    a biased marginal gives streaming training a learnable signal, so
    loss curves on the stream mean something. ``bias="uniform"`` matches
    ``synthetic_batch``'s distribution.
    """
    rng = np.random.default_rng(seed)
    if bias not in ("zipf", "uniform"):
        raise ValueError(f"unknown bias {bias!r}; use zipf|uniform")
    p = None
    if bias == "zipf":
        p = 1.0 / np.arange(1, cfg.vocab + 1)
        p /= p.sum()
    while True:
        stream = rng.choice(
            cfg.vocab, size=(cfg.batch, cfg.seq_len + 1), p=p
        ).astype(np.int32)
        yield stream[:, :-1], stream[:, 1:]


def prefetch_to_device(batches: Iterator[Any], rules=None,
                       size: int = 2) -> Iterator[Any]:
    """Keep ``size`` batches in flight to the device ahead of the consumer.

    Pytree-generic: every leaf is ``device_put`` — with ``rules``, each
    leaf gets the batch sharding TRUNCATED to its own rank (batch dim
    over the data axes, remaining dims replicated), so token arrays,
    per-example lengths, and scalars all place correctly. Because
    ``device_put`` is asynchronous, the window means batch ``i+1``'s
    host→device copy runs while the step computes on batch ``i``.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")

    def leaf_sharding(x):
        if rules is None:
            return None
        ndim = getattr(x, "ndim", 0)
        spec = ((pspec_axes(rules.data),) + (None,) * (ndim - 1)) \
            if ndim else ()
        return rules.shard(P(*spec))

    def place(batch):
        return jax.tree.map(
            lambda x: jax.device_put(x, leaf_sharding(x))
            if rules is not None else jax.device_put(x), batch)

    window: collections.deque = collections.deque()
    for batch in batches:
        window.append(place(batch))
        if len(window) >= size:
            yield window.popleft()
    while window:
        yield window.popleft()


def input_pipeline(cfg, rules=None, seed: int = 0,
                   prefetch: int = 2, bias: str = "zipf") -> Iterator[Any]:
    """``token_stream`` → ``prefetch_to_device``: the assembled pipeline."""
    return prefetch_to_device(token_stream(cfg, seed, bias), rules, prefetch)
