# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Recursive-descent parser: tokens → Body / expression AST."""

from __future__ import annotations

from . import ast as A
from .lexer import Token, tokenize


class HclParseError(SyntaxError):
    pass


_KEYWORD_LITERALS = {"true": True, "false": False, "null": None}


class Parser:
    def __init__(self, tokens: list[Token], filename: str = "<hcl>"):
        self.toks = tokens
        self.pos = 0
        self.filename = filename

    # ------------------------------------------------------------- helpers
    def peek(self, offset: int = 0) -> Token:
        return self.toks[min(self.pos + offset, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "EOF":
            self.pos += 1
        return t

    def skip_newlines(self):
        while self.peek().kind == "NEWLINE":
            self.next()

    def expect(self, kind: str, value: str | None = None) -> Token:
        t = self.next()
        if t.kind != kind or (value is not None and t.value != value):
            self.err(f"expected {value or kind}, got {t}", t)
        return t

    def at_op(self, value: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.value == value

    def eat_op(self, value: str) -> bool:
        if self.at_op(value):
            self.next()
            return True
        return False

    def err(self, msg: str, tok: Token | None = None):
        t = tok or self.peek()
        raise HclParseError(f"{self.filename}:{t.line}: {msg}")

    # ---------------------------------------------------------------- body
    def parse_body(self, until: str | None = None) -> A.Body:
        attrs: list[A.Attribute] = []
        blocks: list[A.Block] = []
        self.skip_newlines()
        first = self.peek()
        while True:
            self.skip_newlines()
            t = self.peek()
            if t.kind == "EOF":
                if until:
                    self.err(f"unexpected EOF, expected {until!r}")
                break
            if until and t.kind == "OP" and t.value == until:
                break
            if t.kind != "IDENT":
                self.err(f"expected attribute or block, got {t}")
            # lookahead: `ident =` → attribute; `ident (STRING|IDENT)* {` → block
            if self.peek(1).kind == "OP" and self.peek(1).value == "=":
                name = self.next().value
                self.next()  # '='
                expr = self.parse_expr()
                attrs.append(A.Attribute(name, expr, line=t.line))
                self._end_of_item()
            else:
                blocks.append(self.parse_block())
        return A.Body(attrs, blocks, line=first.line)

    def _end_of_item(self):
        t = self.peek()
        if t.kind in ("NEWLINE", "EOF"):
            return
        if t.kind == "OP" and t.value in ("}",):
            return
        self.err(f"expected newline after item, got {t}")

    def parse_block(self) -> A.Block:
        t = self.expect("IDENT")
        labels: list[str] = []
        while self.peek().kind in ("STRING", "IDENT"):
            labels.append(self.next().value)
        self.expect("OP", "{")
        body = self.parse_body(until="}")
        self.expect("OP", "}")
        return A.Block(t.value, labels, body, line=t.line)

    # ---------------------------------------------------------- expressions
    def parse_expr(self) -> A.Expr:
        return self.parse_conditional()

    def _at_op_through_newlines(self, value: str) -> bool:
        """True if the next non-newline token is OP(value); consumes the
        newlines when it is. Safe for '?'/':' — no body item or collection
        element can begin with them."""
        off = 0
        while self.peek(off).kind == "NEWLINE":
            off += 1
        t = self.peek(off)
        if t.kind == "OP" and t.value == value:
            self.skip_newlines()
            return True
        return False

    def parse_conditional(self) -> A.Expr:
        cond = self.parse_binary(0)
        if self._at_op_through_newlines("?") and self.eat_op("?"):
            self.skip_newlines()
            t = self.parse_expr()
            self.skip_newlines()
            self.expect("OP", ":")
            self.skip_newlines()
            f = self.parse_expr()
            return A.Conditional(cond, t, f, line=cond.line)
        return cond

    _PRECEDENCE = [
        ["||"],
        ["&&"],
        ["==", "!="],
        ["<", ">", "<=", ">="],
        ["+", "-"],
        ["*", "/", "%"],
    ]

    def parse_binary(self, level: int) -> A.Expr:
        if level >= len(self._PRECEDENCE):
            return self.parse_unary()
        left = self.parse_binary(level + 1)
        while self.peek().kind == "OP" and self.peek().value in self._PRECEDENCE[level]:
            op = self.next().value
            self.skip_newlines()
            right = self.parse_binary(level + 1)
            left = A.Binary(op, left, right, line=left.line)
        return left

    def parse_unary(self) -> A.Expr:
        t = self.peek()
        if t.kind == "OP" and t.value in ("!", "-"):
            self.next()
            return A.Unary(t.value, self.parse_unary(), line=t.line)
        return self.parse_postfix()

    def parse_postfix(self) -> A.Expr:
        expr = self.parse_primary()
        while True:
            if self.at_op("."):
                # `.` then ident / number (tuple index) / `*` splat
                self.next()
                nt = self.next()
                if nt.kind == "IDENT":
                    expr = self._attach(expr, ("attr", nt.value))
                elif nt.kind == "NUMBER":
                    expr = self._attach(expr, ("index", A.Literal(int(nt.value), line=nt.line)))
                elif nt.kind == "OP" and nt.value == "*":
                    expr = self._attach(expr, ("splat",))
                else:
                    self.err(f"bad traversal after '.': {nt}", nt)
            elif self.at_op("["):
                self.next()
                if self.eat_op("*"):
                    self.expect("OP", "]")
                    expr = self._attach(expr, ("splat",))
                else:
                    idx = self.parse_expr()
                    self.expect("OP", "]")
                    expr = self._attach(expr, ("index", idx))
            else:
                return expr

    def _attach(self, expr: A.Expr, op: tuple) -> A.Expr:
        if isinstance(expr, A.Traversal):
            expr.ops.append(op)
            return expr
        # non-traversal base (e.g. function call result, tuple literal)
        t = A.Traversal("", [op], line=expr.line)
        t.root_expr = expr  # type: ignore[attr-defined]
        return t

    def parse_primary(self) -> A.Expr:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            v = float(t.value) if ("." in t.value or "e" in t.value.lower()) else int(t.value)
            return A.Literal(v, line=t.line)
        if t.kind == "STRING":
            self.next()
            return self._parse_template(t)
        if t.kind == "HEREDOC":
            self.next()
            return self._parse_template(t)
        if t.kind == "IDENT":
            if t.value in _KEYWORD_LITERALS:
                self.next()
                return A.Literal(_KEYWORD_LITERALS[t.value], line=t.line)
            if t.value == "for":
                self.err("for-expression outside [ ] / { }")
            # function call?
            if self.peek(1).kind == "OP" and self.peek(1).value == "(":
                return self.parse_call()
            self.next()
            return A.Traversal(t.value, [], line=t.line)
        if t.kind == "OP":
            if t.value == "(":
                self.next()
                self.skip_newlines()
                inner = self.parse_expr()
                self.skip_newlines()
                self.expect("OP", ")")
                return inner
            if t.value == "[":
                return self.parse_tuple()
            if t.value == "{":
                return self.parse_object()
        self.err(f"unexpected token in expression: {t}")

    def parse_call(self) -> A.Expr:
        name = self.expect("IDENT").value
        self.expect("OP", "(")
        args: list[A.Expr] = []
        expand = False
        self.skip_newlines()
        while not self.at_op(")"):
            args.append(self.parse_expr())
            if self.eat_op("..."):
                expand = True
                self.skip_newlines()
                break
            if not self.eat_op(","):
                self.skip_newlines()
                break
            self.skip_newlines()
        self.skip_newlines()
        self.expect("OP", ")")
        return A.Call(name, args, expand_last=expand)

    def parse_tuple(self) -> A.Expr:
        t = self.expect("OP", "[")
        self.skip_newlines()
        if self.peek().kind == "IDENT" and self.peek().value == "for":
            fe = self.parse_for(object_form=False)
            self.expect("OP", "]")
            return fe
        items: list[A.Expr] = []
        while not self.at_op("]"):
            items.append(self.parse_expr())
            self.skip_newlines()
            if not self.eat_op(","):
                self.skip_newlines()
                break
            self.skip_newlines()
        self.expect("OP", "]")
        return A.TupleExpr(items, line=t.line)

    def parse_object(self) -> A.Expr:
        t = self.expect("OP", "{")
        self.skip_newlines()
        if self.peek().kind == "IDENT" and self.peek().value == "for":
            fe = self.parse_for(object_form=True)
            self.expect("OP", "}")
            return fe
        items: list[A.ObjectItem] = []
        while not self.at_op("}"):
            key_tok = self.peek()
            if key_tok.kind == "IDENT" and self.peek(1).kind == "OP" and \
                    self.peek(1).value in ("=", ":"):
                self.next()
                key: A.Expr = A.Literal(key_tok.value, line=key_tok.line)
            elif key_tok.kind == "STRING" and self.peek(1).kind == "OP" and \
                    self.peek(1).value in ("=", ":"):
                self.next()
                key = self._parse_template(key_tok)
            elif self.eat_op("("):
                key = self.parse_expr()
                self.expect("OP", ")")
            else:
                key = self.parse_expr()
            op = self.next()
            if not (op.kind == "OP" and op.value in ("=", ":")):
                self.err(f"expected '=' or ':' in object, got {op}", op)
            self.skip_newlines()
            value = self.parse_expr()
            items.append(A.ObjectItem(key, value, line=key_tok.line))
            self.skip_newlines()
            self.eat_op(",")
            self.skip_newlines()
        self.expect("OP", "}")
        return A.ObjectExpr(items, line=t.line)

    def parse_for(self, object_form: bool) -> A.ForExpr:
        t = self.expect("IDENT")  # 'for'
        v1 = self.expect("IDENT").value
        key_var = None
        value_var = v1
        if self.eat_op(","):
            key_var = v1
            value_var = self.expect("IDENT").value
        in_kw = self.expect("IDENT")
        if in_kw.value != "in":
            self.err("expected 'in' in for-expression", in_kw)
        coll = self.parse_expr()
        self.expect("OP", ":")
        self.skip_newlines()
        key_expr = None
        grouping = False
        first = self.parse_expr()
        if object_form and self.eat_op("=>"):
            key_expr = first
            value_expr = self.parse_expr()
            if self.eat_op("..."):
                grouping = True
        else:
            value_expr = first
        cond = None
        self.skip_newlines()
        if self.peek().kind == "IDENT" and self.peek().value == "if":
            self.next()
            cond = self.parse_expr()
        self.skip_newlines()
        return A.ForExpr(key_var, value_var, coll, key_expr, value_expr, cond,
                         grouping, line=t.line)

    # ------------------------------------------------------------ templates
    def _parse_template(self, tok: Token) -> A.Expr:
        """Split a raw string token into literal/interp parts."""
        raw = tok.value
        parts: list = []
        buf: list[str] = []
        i, n = 0, len(raw)
        while i < n:
            if raw[i] == "\\" and tok.kind == "STRING" and i + 1 < n:
                esc = raw[i + 1]
                buf.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc, "\\" + esc))
                i += 2
                continue
            if raw.startswith("$${", i) or raw.startswith("%%{", i):
                buf.append(raw[i + 1 :][: 2])
                i += 3
                continue
            if raw.startswith("${", i):
                # find matching close brace, skipping nested string literals
                # (a `}` inside "..." must not close the interpolation)
                depth, j = 1, i + 2
                in_str = False
                while j < n and depth:
                    ch = raw[j]
                    if in_str:
                        if ch == "\\":
                            j += 2
                            continue
                        if ch == '"':
                            in_str = False
                    elif ch == '"':
                        in_str = True
                    elif ch == "{":
                        depth += 1
                    elif ch == "}":
                        depth -= 1
                    j += 1
                if depth:
                    self.err("unterminated interpolation", tok)
                inner_src = raw[i + 2 : j - 1]
                sub = Parser(tokenize(inner_src, self.filename), self.filename)
                sub.skip_newlines()
                expr = sub.parse_expr()
                if buf:
                    parts.append("".join(buf))
                    buf = []
                parts.append(expr)
                i = j
                continue
            if raw.startswith("%{", i):
                # template directives (%{ if } / %{ for }) — out of subset
                self.err("template directives %{...} not supported by tfsim", tok)
            buf.append(raw[i])
            i += 1
        if buf:
            parts.append("".join(buf))
        if len(parts) == 1 and isinstance(parts[0], str):
            return A.Literal(parts[0], line=tok.line)
        if not parts:
            return A.Literal("", line=tok.line)
        return A.Template(parts, line=tok.line)


def parse_hcl(src: str, filename: str = "<hcl>") -> A.Body:
    p = Parser(tokenize(src, filename), filename)
    return p.parse_body()


def parse_expression(src: str, filename: str = "<expr>") -> A.Expr:
    p = Parser(tokenize(src, filename), filename)
    p.skip_newlines()
    return p.parse_expr()
