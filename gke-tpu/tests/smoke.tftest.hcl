# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Native-format test suite for the gke-tpu module, run by `tfsim test`
# (offline analogue of `terraform test`). Covers the BASELINE.json target
# configs the way tests/test_gke_tpu_module.py does from Python — these
# run blocks are the terraform-idiomatic face of the same golden plans.

variables {
  project_id   = "test-project"
  cluster_name = "tpu-test"
}

# BASELINE config 3 is the module default: one v5e 2x4 multi-host slice.
run "default_v5e8" {
  command = plan

  assert {
    condition     = output.tpu_slices["default"].machine_type == "ct5lp-hightpu-4t"
    error_message = "v5e 2x4 must derive the 4-chip host type"
  }
  assert {
    condition     = output.tpu_slices["default"].hosts == 2
    error_message = "v5e 2x4 is a 2-host slice"
  }
  assert {
    condition     = output.total_tpu_chips == 8
    error_message = "default fleet should expose 8 chips"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["default"].node_count == 2
    error_message = "slice pools are atomic: node_count must equal hosts"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["default"].placement_policy[0].tpu_topology == "2x4"
    error_message = "multi-host slices need COMPACT placement with the slice topology"
  }
  assert {
    condition     = kubernetes_job_v1.tpu_smoketest["default"].spec[0].completions == 2
    error_message = "smoketest Job runs one indexed pod per slice host"
  }
  assert {
    condition     = kubernetes_job_v1.tpu_smoketest["default"].wait_for_completion == true
    error_message = "apply must gate on smoketest completion (the north-star metric)"
  }
}

# BASELINE config 2: single-host v5e-1 — no placement policy, no coordinator
# choreography needed.
run "single_host_v5e1" {
  command = plan

  variables {
    tpu_slices = {
      default = { version = "v5e", topology = "1x1" }
    }
  }

  assert {
    condition     = output.tpu_slices["default"].machine_type == "ct5lp-hightpu-1t"
    error_message = "v5e 1x1 is the single-chip host type"
  }
  assert {
    condition     = output.tpu_slices["default"].multi_host == false
    error_message = "1x1 must not be multi-host"
  }
  assert {
    condition     = !contains(keys(google_container_node_pool.tpu_slice["default"]), "placement_policy")
    error_message = "single-host slices must not set a placement policy"
  }
}

# BASELINE config 5: v4 pod slice under node-auto-provisioning, spot.
run "v4_pod_slice_nap" {
  command = plan

  variables {
    tpu_slices = {
      train = { version = "v4", topology = "2x2x4", spot = true }
    }
    node_auto_provisioning = {
      enabled = true
      resource_limits = [
        { resource_type = "tpu-v4-podslice-chips", maximum = 64 },
      ]
    }
    smoketest = { enabled = false }
  }

  assert {
    condition     = google_container_node_pool.tpu_slice["train"].node_config[0].machine_type == "ct4p-hightpu-4t"
    error_message = "v4 2x2x4 must derive the ct4p 4-chip host type"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["train"].node_config[0].spot == true
    error_message = "spot flag must reach the node config"
  }
  assert {
    condition     = google_container_cluster.this.cluster_autoscaling[0].resource_limits[0].resource_type == "tpu-v4-podslice-chips"
    error_message = "NAP resource limits must pass through to cluster_autoscaling"
  }
  assert {
    condition     = length(kubernetes_job_v1.tpu_smoketest) == 0
    error_message = "disabling the smoketest must plan no Job"
  }
}

# v5p multi-host: 2x2x2 = 8 chips on fixed 4-chip hosts → a 2-host slice
# with COMPACT placement (the generation's machine prefix differs from
# v5e's; this run pins the whole derivation chain for v5p).
run "v5p_multi_host" {
  command = plan

  variables {
    tpu_slices = {
      train = { version = "v5p", topology = "2x2x2" }
    }
  }

  assert {
    condition     = output.tpu_slices["train"].machine_type == "ct5p-hightpu-4t"
    error_message = "v5p 2x2x2 must derive the ct5p 4-chip host type"
  }
  assert {
    condition     = output.tpu_slices["train"].hosts == 2 && output.tpu_slices["train"].total_chips == 8
    error_message = "v5p 2x2x2 is 8 chips across 2 hosts"
  }
  assert {
    condition     = output.tpu_slices["train"].multi_host == true
    error_message = "a 2-host v5p slice is multi-host"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["train"].placement_policy[0].type == "COMPACT"
    error_message = "multi-host v5p needs COMPACT placement"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["train"].placement_policy[0].tpu_topology == "2x2x2"
    error_message = "placement must carry the slice topology"
  }
  assert {
    condition     = kubernetes_job_v1.tpu_smoketest["train"].spec[0].completions == 2
    error_message = "v5p smoketest Job must run one indexed pod per host"
  }
}

# v6e-8 single-host: prefer_single_host packs 2x4 = 8 chips onto ONE
# ct6e-standard-8t host — no placement policy, no multi-host choreography.
run "v6e_prefer_single_host" {
  command = plan

  variables {
    tpu_slices = {
      serve = { version = "v6e", topology = "2x4", prefer_single_host = true }
    }
  }

  assert {
    condition     = output.tpu_slices["serve"].machine_type == "ct6e-standard-8t"
    error_message = "v6e 2x4 with prefer_single_host must pack onto the 8-chip host"
  }
  assert {
    condition     = output.tpu_slices["serve"].hosts == 1 && output.tpu_slices["serve"].total_chips == 8
    error_message = "prefer_single_host packs all 8 chips on one host"
  }
  assert {
    condition     = output.tpu_slices["serve"].multi_host == false
    error_message = "an 8t-packed v6e slice is single-host"
  }
  assert {
    condition     = !contains(keys(google_container_node_pool.tpu_slice["serve"]), "placement_policy")
    error_message = "single-host v6e must not set a placement policy"
  }
  assert {
    condition     = output.tpu_slices["serve"].node_selectors["cloud.google.com/gke-tpu-accelerator"] == "tpu-v6e-slice"
    error_message = "v6e pools must carry the v6e node selector"
  }
}

# The same v6e topology WITHOUT prefer_single_host must fall back to the
# multi-host 4t layout — the packing is opt-in.
run "v6e_default_multi_host" {
  command = plan

  variables {
    tpu_slices = {
      serve = { version = "v6e", topology = "2x4" }
    }
  }

  assert {
    condition     = output.tpu_slices["serve"].machine_type == "ct6e-standard-4t"
    error_message = "v6e 2x4 without packing must use the 4-chip host type"
  }
  assert {
    condition     = output.tpu_slices["serve"].hosts == 2 && output.tpu_slices["serve"].multi_host == true
    error_message = "unpacked v6e 2x4 is a 2-host slice"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["serve"].placement_policy[0].tpu_topology == "2x4"
    error_message = "unpacked v6e needs COMPACT placement with the topology"
  }
}

# Queued provisioning (DWS flex-start): the pool starts empty and GKE
# scales it to the whole slice atomically when capacity arrives — the
# realistic acquisition path when no reservation is held.
run "queued_provisioning_slice" {
  command = plan

  variables {
    tpu_slices = {
      train = { version = "v5p", topology = "2x2x2", queued_provisioning = true }
    }
  }

  assert {
    condition     = google_container_node_pool.tpu_slice["train"].queued_provisioning[0].enabled == true
    error_message = "queued_provisioning flag must reach the pool block"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["train"].initial_node_count == 0
    error_message = "a queued pool must start empty (DWS scales it up)"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["train"].autoscaling[0].total_max_node_count == 2
    error_message = "DWS autoscaling ceiling must be the slice's host count"
  }
  assert {
    condition     = google_container_node_pool.tpu_slice["train"].autoscaling[0].location_policy == "ANY"
    error_message = "queued pools use location policy ANY per the DWS recipe"
  }
  assert {
    condition     = !contains(keys(google_container_node_pool.tpu_slice["train"]), "node_count")
    error_message = "queued pools must not pin node_count (DWS owns the size)"
  }
}

# A queued slice cannot also be spot/reserved — it IS the capacity mode.
run "queued_provisioning_conflicts" {
  command = plan

  variables {
    tpu_slices = {
      bad = { queued_provisioning = true, spot = true }
    }
  }

  expect_failures = [var.tpu_slices]
}

# The negative path: spot and reservation are mutually exclusive per slice
# (variable validation), so the plan itself must fail.
run "spot_reservation_conflict" {
  command = plan

  variables {
    tpu_slices = {
      bad = { spot = true, reservation = "my-resv" }
    }
  }

  expect_failures = [var.tpu_slices]
}

# Control-plane security: CMEK secrets encryption (reference EKS
# eks/main.tf:64-72 parity) and Google Groups RBAC (reference AKS
# aks/main.tf:36-40 parity).
run "secrets_encryption_creates_key_and_grant" {
  command = plan

  variables {
    database_encryption          = { enabled = true }
    authenticator_security_group = "gke-security-groups@example.com"
  }

  assert {
    condition     = google_container_cluster.this.database_encryption[0].state == "ENCRYPTED"
    error_message = "enabled CMEK must render an ENCRYPTED database_encryption block"
  }
  assert {
    condition     = length(google_kms_key_ring.secrets) == 1 && length(google_kms_crypto_key.secrets) == 1
    error_message = "no BYO key: the module must create keyring + crypto key"
  }
  assert {
    condition     = google_kms_crypto_key.secrets[0].rotation_period == "7776000s"
    error_message = "created key must rotate (reference enable_key_rotation parity)"
  }
  assert {
    condition     = length(google_kms_crypto_key_iam_member.gke_agent) == 1
    error_message = "the GKE service agent needs EncrypterDecrypter on the key"
  }
  assert {
    condition     = google_container_cluster.this.authenticator_groups_config[0].security_group == "gke-security-groups@example.com"
    error_message = "the RBAC umbrella group must reach the control plane"
  }
}

run "secrets_encryption_byo_key" {
  command = plan

  variables {
    database_encryption = {
      enabled      = true
      kms_key_name = "projects/p/locations/r/keyRings/kr/cryptoKeys/k"
    }
  }

  assert {
    condition     = length(google_kms_key_ring.secrets) == 0 && length(google_kms_crypto_key.secrets) == 0
    error_message = "BYO key must not create module-owned KMS resources"
  }
  assert {
    condition     = google_container_cluster.this.database_encryption[0].key_name == "projects/p/locations/r/keyRings/kr/cryptoKeys/k"
    error_message = "the BYO key must reach the cluster block verbatim"
  }
}

# An unrendered dynamic block reads as provider-computed in the simulator,
# so "defaults off" is asserted through the countable module-owned
# resources the feature would have created.
run "security_defaults_off" {
  command = plan

  assert {
    condition     = length(google_kms_key_ring.secrets) == 0 && length(google_kms_crypto_key.secrets) == 0
    error_message = "no KMS resources unless encryption is enabled"
  }
  assert {
    condition     = length(google_kms_crypto_key_iam_member.gke_agent) == 0
    error_message = "no service-agent grant unless encryption is enabled"
  }
}
