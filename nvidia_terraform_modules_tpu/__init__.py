# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""nvidia_terraform_modules_tpu — TPU-native cluster-validation & IaC-test library.

This package is the *runtime* half of the tpu-terraform-modules framework. The
reference project (``nvidia-terraform-modules``) ships only declarative HCL and
delegates accelerator validation to manual runbooks (see
``/root/reference/gke/README.md:50``, ``/root/reference/eks/examples/cnpack/Readme.md:107-163``).
We replace those runbooks with executable code:

- :mod:`~nvidia_terraform_modules_tpu.smoketest` — the in-cluster JAX ``psum``
  all-reduce validation Job payload (single-host and multi-host slices).
- :mod:`~nvidia_terraform_modules_tpu.models` — the burn-in workload (a small
  sharded transformer) used to prove a freshly provisioned slice trains.
- :mod:`~nvidia_terraform_modules_tpu.ops` — MXU/HBM/ICI micro-probes used by
  ``bench.py`` and the smoke test.
- :mod:`~nvidia_terraform_modules_tpu.parallel` — mesh construction, sharding
  rules and multi-host bootstrap for GKE indexed Jobs / JobSets.
- :mod:`~nvidia_terraform_modules_tpu.tfsim` — an offline Terraform module
  validator (HCL2 parser + plan-graph simulator) standing in for
  ``terraform fmt/validate/plan`` golden tests where no cloud or terraform
  binary is available (the reference has no automated tests at all —
  ``/root/reference/CONTRIBUTING.md:56``).
"""

__version__ = "0.7.0"
