# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Golden-plan tests for the gke/ (GPU-parity) module via tfsim.

The offline analogue of `terraform validate` + plan-fixture testing
(SURVEY.md §4: the reference has no automated tests; these are ours).
"""

import os

import pytest

from nvidia_terraform_modules_tpu.tfsim import (
    load_module,
    simulate_plan,
    validate_module,
)
from nvidia_terraform_modules_tpu.tfsim.plan import PlanError, render


@pytest.fixture(scope="module")
def gke(repo_root_mod):
    return load_module(os.path.join(repo_root_mod, "gke"))


@pytest.fixture(scope="module")
def repo_root_mod():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


BASE_VARS = {"project_id": "proj-x", "cluster_name": "demo"}


def test_validate_no_errors(gke):
    findings = validate_module(gke)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], [str(e) for e in errors]


def test_validate_no_warnings(gke):
    # style gate: every variable/output described & typed, providers pinned
    findings = validate_module(gke)
    assert findings == [], [str(f) for f in findings]


def test_default_plan_shape(gke):
    plan = simulate_plan(gke, dict(BASE_VARS))
    addrs = set(plan.instances)
    assert "google_compute_network.vpc[0]" in addrs
    assert "google_compute_subnetwork.cluster[0]" in addrs
    assert "google_container_cluster.this" in addrs
    assert "google_container_node_pool.cpu" in addrs
    assert "google_container_node_pool.gpu[0]" in addrs
    assert "kubernetes_namespace_v1.gpu_operator[0]" in addrs
    assert "kubernetes_resource_quota_v1.operator_pods[0]" in addrs
    assert "helm_release.gpu_operator[0]" in addrs


def test_zonal_vs_regional(gke):
    zonal = simulate_plan(gke, dict(BASE_VARS))
    assert zonal.instance("google_container_cluster.this").attrs[
        "location"] == "us-central1-a"
    regional = simulate_plan(gke, {
        **BASE_VARS, "node_zones": ["us-central1-a", "us-central1-b"]})
    assert regional.instance("google_container_cluster.this").attrs[
        "location"] == "us-central1"


def test_cpu_only_baseline_config(gke):
    """BASELINE config 1: CPU-only pool, operator disabled."""
    plan = simulate_plan(gke, {
        **BASE_VARS,
        "gpu_pool": {"enabled": False},
    })
    addrs = set(plan.instances)
    assert "google_container_node_pool.cpu" in addrs
    assert not any(a.startswith("google_container_node_pool.gpu") for a in addrs)
    assert not any(a.startswith("helm_release") for a in addrs)
    assert not any(a.startswith("kubernetes_namespace") for a in addrs)
    assert plan.outputs["gpu_pool_name"] is None


def test_byo_network(gke):
    plan = simulate_plan(gke, {
        **BASE_VARS,
        "network": {
            "create": False,
            "existing_network": "shared-vpc",
            "existing_subnetwork": "shared-subnet",
        },
    })
    assert not any(a.startswith("google_compute_network") for a in plan.instances)
    cluster = plan.instance("google_container_cluster.this")
    assert cluster.attrs["network"] == "shared-vpc"
    assert cluster.attrs["subnetwork"] == "shared-subnet"


def test_gpu_pool_accelerator_config(gke):
    plan = simulate_plan(gke, {
        **BASE_VARS,
        "gpu_pool": {"gpu_type": "nvidia-l4", "gpu_count": 2, "spot": True},
    })
    gpu = plan.instance("google_container_node_pool.gpu[0]")
    acc = gpu.attrs["node_config"][0]["guest_accelerator"][0]
    assert acc == {"type": "nvidia-l4", "count": 2}
    assert gpu.attrs["node_config"][0]["spot"] is True
    # optional() defaults preserved for attrs not overridden
    assert gpu.attrs["node_config"][0]["machine_type"] == "n1-standard-8"


def test_operator_pinning_flows_to_release(gke):
    plan = simulate_plan(gke, {
        **BASE_VARS,
        "gpu_operator": {"version": "v25.3.1", "driver_version": "999.1"},
    })
    rel = plan.instance("helm_release.gpu_operator[0]")
    assert rel.attrs["version"] == "v25.3.1"
    assert rel.attrs["set"][0] == {"name": "driver.version", "value": "999.1"}
    assert rel.attrs["atomic"] is True
    assert rel.attrs["cleanup_on_fail"] is True


def test_apply_order_cluster_before_pools_before_operator(gke):
    plan = simulate_plan(gke, dict(BASE_VARS))
    o = plan.order
    assert o.index("google_container_cluster.this") < o.index(
        "google_container_node_pool.gpu")
    assert o.index("google_container_node_pool.gpu") < o.index(
        "kubernetes_namespace_v1.gpu_operator")
    assert o.index("kubernetes_resource_quota_v1.operator_pods") < o.index(
        "helm_release.gpu_operator")


def test_empty_zones_rejected(gke):
    with pytest.raises(PlanError) as ei:
        simulate_plan(gke, {**BASE_VARS, "node_zones": []})
    assert "node zone" in str(ei.value).lower()


def test_release_channel_unspecified_pins_version(gke):
    plan = simulate_plan(gke, {
        **BASE_VARS,
        "release_channel": "UNSPECIFIED",
        "min_master_version": "1.29.1",
    })
    cluster = plan.instance("google_container_cluster.this")
    assert cluster.attrs["min_master_version"] == "1.29.1"
    assert "release_channel" not in cluster.attrs  # dynamic block empty
