"""Benchmark: time-to-validated-accelerator, plus MXU/HBM roofline probes.

The reference publishes no benchmark numbers (BASELINE.md). Its only
quantitative operational claim is that the GPU Operator needs **~5 minutes**
after ``terraform apply`` before the accelerator stack is usable, and even then
validation is a human running ``kubectl get pods``
(``/root/reference/gke/README.md:50``). Our equivalent stage — the smoke-test
Job payload that proves devices, collectives, and a sharded train step all work
— is fully automated, so the headline metric is how long that validation takes
on the chip: lower is better, baseline is the reference's 300 s manual wait.

Prints ONE JSON line:
  metric       accelerator_validation_seconds (lower is better)
  vs_baseline  300 / value  (×-faster than the reference's operator wait)
plus secondary fields: achieved bf16 matmul TFLOP/s, HBM GiB/s, psum status.
Runs on whatever ``jax.devices()`` exposes (one real TPU chip under the
driver; the virtual CPU mesh during offline development).
"""

from __future__ import annotations

import json
import time


REFERENCE_OPERATOR_WAIT_S = 300.0  # /root/reference/gke/README.md:50 ("~5 min")


def main() -> None:
    import jax

    t0 = time.perf_counter()

    from nvidia_terraform_modules_tpu.ops import hbm_probe, matmul_probe
    from nvidia_terraform_modules_tpu.smoketest import run_smoketest

    n_dev = len(jax.devices())
    level = "burnin" if n_dev >= 2 else "psum"
    smoke = run_smoketest(level=level, env={})
    validation_seconds = time.perf_counter() - t0  # import→verdict, the metric

    on_tpu = jax.devices()[0].platform == "tpu"
    mm = matmul_probe(n=4096 if on_tpu else 512, iters=8 if on_tpu else 2)
    hbm = hbm_probe(mib=512 if on_tpu else 32, iters=8 if on_tpu else 2,
                    mode="read")
    hbm_triad = hbm_probe(mib=512 if on_tpu else 32,
                          iters=8 if on_tpu else 2, mode="triad")

    # workload-level number: train-step MFU at long context on the flash
    # path (VERDICT round-1 item 2) — achieved model FLOP/s over the chip's
    # bf16 peak, on a config big enough for the matmuls to dominate
    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        make_train_step,
        synthetic_batch,
        train_step_flops,
    )
    from nvidia_terraform_modules_tpu.utils.device import device_spec
    import jax.numpy as jnp

    cfg = (
        # head_dim 128 fills the MXU lane width inside the flash kernel;
        # d=2048 projections/MLP dominate the FLOPs. Measured on v5e
        # (2026-07 sweep): 0.65 MFU here vs 0.29 at d=1024/head_dim=64.
        BurnInConfig(vocab=8192, d_model=2048, n_heads=16, d_ff=8192,
                     n_layers=8, seq_len=4096, batch=2, attn="flash")
        if on_tpu
        else BurnInConfig(vocab=256, d_model=64, n_heads=4, d_ff=128,
                          n_layers=2, seq_len=32, batch=4, dtype=jnp.float32)
    )
    from nvidia_terraform_modules_tpu.utils.timing import sync

    params = init_params(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)
    params, loss = step(params, batch)  # compile
    sync(loss)
    t_step = time.perf_counter()
    iters = 10
    for _ in range(iters):
        params, loss = step(params, batch)
    sync(loss)  # d2h readback: the only reliable barrier on tunnelled backends
    step_seconds = (time.perf_counter() - t_step) / iters
    tokens_per_s = cfg.batch * cfg.seq_len / step_seconds
    mfu = (train_step_flops(cfg) / step_seconds) / (
        device_spec().bf16_tflops * 1e12)

    # serve-side: greedy KV-cache decode throughput (HBM-bound regime —
    # weights + cache re-read every step; the serving counterpart of the
    # train-step MFU above)
    import dataclasses

    from nvidia_terraform_modules_tpu.models import make_decoder

    # same model as the burn-in MFU measurement (one source of truth for
    # the flagship dims), decode-shaped: dense cached attention, batch 8.
    # The trained weights are reused — attn/batch don't change parameter
    # shapes, and a second full init would double weight HBM for no reason.
    dec_cfg = dataclasses.replace(cfg, attn="dense",
                                  batch=8 if on_tpu else cfg.batch)
    prompt_len, n_new = (512, 64) if on_tpu else (8, 8)
    dec_params = params
    max_len = prompt_len + n_new
    decoder = make_decoder(dec_cfg, n_new=n_new, max_len=max_len)
    # prefill-only twin (n_new=1 → zero scan steps): subtracting its time
    # isolates the HBM-bound per-step decode cost from the MXU-bound
    # prompt forward, so decode_tokens_per_s measures what it claims
    prefiller = make_decoder(dec_cfg, n_new=1, max_len=max_len)
    prompt = jax.random.randint(jax.random.PRNGKey(3),
                                (dec_cfg.batch, prompt_len), 0,
                                dec_cfg.vocab)
    sync(decoder(dec_params, prompt))    # compile
    sync(prefiller(dec_params, prompt))  # compile
    dec_iters = 3
    t_dec = time.perf_counter()
    for _ in range(dec_iters):
        toks = decoder(dec_params, prompt)
    sync(toks)
    t_total = (time.perf_counter() - t_dec) / dec_iters
    t_pre = time.perf_counter()
    for _ in range(dec_iters):
        toks = prefiller(dec_params, prompt)
    sync(toks)
    t_prefill = (time.perf_counter() - t_pre) / dec_iters
    step_seconds_dec = max(t_total - t_prefill, 1e-9) / (n_new - 1)
    decode_tokens_per_s = dec_cfg.batch / step_seconds_dec
    prefill_tokens_per_s = dec_cfg.batch * prompt_len / max(t_prefill, 1e-9)

    # weight-only int8 serving: same decode, weights int8-resident in HBM
    # (the decode regime is weight-bandwidth-bound, so this is the lever)
    from nvidia_terraform_modules_tpu.models import (
        make_quantized_decoder,
        quantize_tree,
    )

    qparams = quantize_tree(dec_params)
    q_decoder = make_quantized_decoder(
        dec_cfg, n_new=n_new, max_len=max_len,
        dtype=dec_cfg.dtype)
    # int8 prefill twin: the quantized program's own prefill cost —
    # subtracting the bf16 twin's would fold the dequant/prefill delta
    # into the per-step estimate and skew the side-by-side numbers
    q_prefiller = make_quantized_decoder(
        dec_cfg, n_new=1, max_len=max_len, dtype=dec_cfg.dtype)
    sync(q_decoder(qparams, prompt))     # compile
    sync(q_prefiller(qparams, prompt))   # compile
    t_q = time.perf_counter()
    for _ in range(dec_iters):
        toks = q_decoder(qparams, prompt)
    sync(toks)
    t_q_total = (time.perf_counter() - t_q) / dec_iters
    t_qp = time.perf_counter()
    for _ in range(dec_iters):
        toks = q_prefiller(qparams, prompt)
    sync(toks)
    t_q_prefill = (time.perf_counter() - t_qp) / dec_iters
    q_step = max(t_q_total - t_q_prefill, 1e-9) / (n_new - 1)
    decode_int8_tokens_per_s = dec_cfg.batch / q_step

    # long-context attention: pallas flash kernel vs XLA dense at S=4096 —
    # the regime ring/flash attention exist for (O(S²) HBM traffic dominates)
    longctx: dict[str, float] = {}
    if on_tpu:
        from nvidia_terraform_modules_tpu.ops import flash_attention
        from nvidia_terraform_modules_tpu.ops.ring_attention import (
            dense_reference_attention,
        )
        from nvidia_terraform_modules_tpu.utils.timing import delta_time

        S = 4096
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q, k, v = (jax.random.normal(kk, (2, S, 8, 64), jnp.bfloat16)
                   for kk in ks)

        def make_chain(op):
            def factory(length):
                @jax.jit
                def chain(q, k, v):
                    def s(acc, _):
                        return op(acc, k, v), None
                    out, _ = jax.lax.scan(s, q, None, length=length)
                    return out
                return chain
            return factory

        t_flash = delta_time(make_chain(flash_attention), q, k, v,
                             iters_lo=2, iters_hi=10)
        t_dense = delta_time(make_chain(dense_reference_attention), q, k, v,
                             iters_lo=2, iters_hi=10)
        longctx = {
            "longctx_s": S,
            "longctx_flash_ms": round(t_flash * 1e3, 3),
            "longctx_dense_ms": round(t_dense * 1e3, 3),
            "longctx_flash_speedup": round(t_dense / t_flash, 2),
        }

    line = {
        "metric": "accelerator_validation_seconds",
        "value": round(validation_seconds, 2),
        "unit": "s",
        "vs_baseline": round(REFERENCE_OPERATOR_WAIT_S / validation_seconds, 2),
        "total_seconds": round(time.perf_counter() - t0, 2),
        "smoke_ok": smoke.ok,
        "devices": n_dev,
        "device_kind": jax.devices()[0].device_kind,
        "matmul_tflops": round(mm["tflops"], 2),
        "matmul_roofline": round(mm["roofline_fraction"], 3),
        "hbm_gibps": round(hbm["gibps"], 1),
        "hbm_roofline": round(hbm["roofline_fraction"], 3),
        "hbm_triad_gibps": round(hbm_triad["gibps"], 1),
        "hbm_triad_roofline": round(hbm_triad["roofline_fraction"], 3),
        "burnin_tokens_per_s": round(tokens_per_s, 1),
        "burnin_attn": cfg.attn,
        "burnin_seq_len": cfg.seq_len,
        "burnin_mfu": round(mfu, 3),
        "decode_tokens_per_s": round(decode_tokens_per_s, 1),
        "decode_int8_tokens_per_s": round(decode_int8_tokens_per_s, 1),
        "prefill_tokens_per_s": round(prefill_tokens_per_s, 1),
        "decode_batch": dec_cfg.batch,
        "decode_prompt_len": prompt_len,
        **longctx,
    }
    print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
