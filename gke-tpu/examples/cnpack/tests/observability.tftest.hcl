# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Semantic pins for the TPU observability composition: the Workload
# Identity chains (prometheus, fluentbit, CAS issuer) and the private-CA
# chain shape. These are the values the platform installer consumes — a
# renamed KSA or pool breaks the handoff with no plan-time error, which
# is exactly what these asserts exist to catch.

variables {
  project_id = "test-project"
}

run "prometheus_identity" {
  command = plan

  assert {
    condition     = google_service_account_iam_member.wi_binding.member == "serviceAccount:test-project.svc.id.goog[tpu-monitoring/tpu-prometheus]"
    error_message = "WI member must bind the tpu-monitoring/tpu-prometheus KSA in the target project"
  }
  assert {
    condition     = google_service_account_iam_member.wi_binding.role == "roles/iam.workloadIdentityUser"
    error_message = "the KSA impersonates via roles/iam.workloadIdentityUser"
  }
  assert {
    condition     = google_project_iam_member.metric_writer.role == "roles/monitoring.metricWriter"
    error_message = "the GSA needs metricWriter to remote-write into Managed Prometheus"
  }
  assert {
    condition     = output.monitoring_namespace == "tpu-monitoring"
    error_message = "the namespace output must match the WI binding's namespace"
  }
}

run "cas_chain" {
  command = plan

  assert {
    condition     = google_privateca_ca_pool.cnpack[0].name == "tpu-cnpack-ca-pool"
    error_message = "CAS pool name is derived from cluster_name — the issuer spec references it"
  }
  assert {
    condition     = google_privateca_certificate_authority.cnpack[0].type == "SELF_SIGNED"
    error_message = "the root CA must be self-signed (it heads the chain)"
  }
  assert {
    condition     = google_privateca_certificate_authority.cnpack[0].lifetime == "31536000s"
    error_message = "root validity pinned at 1 year (reference aws-pca.tf:36-39 parity)"
  }
  assert {
    condition     = google_service_account_iam_member.cas_issuer_wi[0].member == "serviceAccount:test-project.svc.id.goog[cert-manager/google-cas-issuer]"
    error_message = "the CAS issuer runs as cert-manager/google-cas-issuer"
  }
  assert {
    condition     = google_privateca_ca_pool_iam_member.cas_issuer_requester[0].role == "roles/privateca.certificateRequester"
    error_message = "issuing rights are certificateRequester scoped to the pool"
  }
}

run "fluentbit_identity" {
  command = plan

  assert {
    condition     = google_service_account_iam_member.fluentbit_wi[0].member == "serviceAccount:test-project.svc.id.goog[tpu-monitoring/tpu-fluentbit]"
    error_message = "Fluent Bit's KSA binding must target tpu-monitoring/tpu-fluentbit"
  }
  assert {
    condition     = google_project_iam_member.fluentbit_log_writer[0].role == "roles/logging.logWriter"
    error_message = "the log shipper writes via roles/logging.logWriter"
  }
}

run "private_ca_disabled_prunes_chain" {
  command = plan

  variables {
    private_ca_enabled = false
  }

  assert {
    condition     = length(google_privateca_ca_pool.cnpack) == 0
    error_message = "private_ca_enabled = false must provision no CAS pool"
  }
  assert {
    condition     = output.ca_pool == null
    error_message = "ca_pool output must be null when the CA is disabled"
  }
}
