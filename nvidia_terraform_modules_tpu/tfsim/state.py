"""State simulation: apply, persist, re-plan, diff — terraform's checkpoint.

SURVEY §5 maps the reference's checkpoint/resume story onto Terraform state:
"apply is resumable/idempotent; remote state recommended but not configured"
(``/root/reference/README.md:89-91``). The reference cannot test any of that
without a live cloud. This module simulates the state lifecycle offline:

- ``apply_plan`` turns a simulated plan into a :class:`State` (the checkpoint);
- ``State.to_json``/``from_json`` round-trip it (the "remote state" file);
- ``diff`` compares a fresh plan against a prior state the way
  ``terraform plan`` reports actions: create / update / delete / no-op.

Semantics mirror Terraform's: provider-computed attributes (``<computed>``)
never drive updates — only config-driven values do — so a re-plan against an
unchanged module is a full no-op (the idempotence/resume guarantee), while a
changed tfvar surfaces as exactly the updates it causes.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from .plan import Plan, render

COMPUTED_STR = "<computed>"


@dataclasses.dataclass
class State:
    """Applied resource attributes by address — the checkpoint artifact."""

    resources: dict[str, Any] = dataclasses.field(default_factory=dict)
    serial: int = 0

    def to_json(self) -> str:
        return json.dumps(
            {"serial": self.serial, "resources": self.resources},
            indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "State":
        raw = json.loads(text)
        return cls(resources=raw["resources"], serial=raw["serial"])


@dataclasses.dataclass
class Diff:
    """Plan-vs-state actions, terraform-plan style."""

    actions: dict[str, str]               # address → create|update|delete|no-op
    changed_keys: dict[str, list[str]]    # address → keys driving an update

    def by_action(self, action: str) -> list[str]:
        return sorted(a for a, act in self.actions.items() if act == action)

    @property
    def is_noop(self) -> bool:
        return all(a == "no-op" for a in self.actions.values())

    def summary(self) -> str:
        c, u, d = (len(self.by_action(a)) for a in ("create", "update", "delete"))
        return f"Plan: {c} to add, {u} to change, {d} to destroy."


_MISSING = object()   # key present in state but absent from the new plan


def _values_match(planned: Any, applied: Any) -> bool:
    """Deep equality where a planned ``<computed>`` matches anything.

    Terraform only diffs config-driven values; attributes the provider fills
    at apply time cannot cause an update on re-plan. A key *removed* from
    config (``_MISSING``) is a change unless the stored value was itself
    provider-computed.
    """
    if planned is _MISSING:
        return applied == COMPUTED_STR
    if planned == COMPUTED_STR:
        return True
    if isinstance(planned, dict) and isinstance(applied, dict):
        # same missing-key rule at every depth: a key gone from config is
        # only a change if its stored value was config-driven
        return all(_values_match(planned.get(k, _MISSING), applied.get(k))
                   for k in set(planned) | set(applied))
    if isinstance(planned, list) and isinstance(applied, list):
        return len(planned) == len(applied) and all(
            _values_match(p, a) for p, a in zip(planned, applied))
    return planned == applied


def _is_data(addr: str) -> bool:
    """True for data sources at any module depth (module.x.data.t.n too)."""
    while addr.startswith("module."):
        addr = addr.split(".", 2)[2]
    return addr.startswith("data.")


def _rendered_instances(plan: Plan) -> dict[str, Any]:
    # data sources are read every run, never tracked — terraform counts
    # neither their reads nor their disappearance as plan actions
    return {addr: render(dict(inst.attrs))
            for addr, inst in plan.instances.items()
            if not _is_data(addr)}


def diff(plan: Plan, state: State | None) -> Diff:
    """What ``terraform apply`` would do to ``state`` to realise ``plan``."""
    planned = _rendered_instances(plan)
    prior = dict(state.resources) if state else {}
    actions: dict[str, str] = {}
    changed: dict[str, list[str]] = {}
    for addr, attrs in planned.items():
        if addr not in prior:
            actions[addr] = "create"
            continue
        keys = sorted(
            k for k in set(attrs) | set(prior[addr])
            if not _values_match(attrs.get(k, _MISSING),
                                 prior[addr].get(k)))
        if keys:
            actions[addr] = "update"
            changed[addr] = keys
        else:
            actions[addr] = "no-op"
    for addr in prior:
        if addr not in planned:
            actions[addr] = "delete"
    return Diff(actions=actions, changed_keys=changed)


def _moved_addr(expr) -> str | None:
    """Render a ``moved`` from/to traversal as a state address.

    Unlike ``path_str`` (diagnostics), index ops render their literal keys —
    ``a.b[1]`` / ``a.b["k"]`` — so instance-keyed moves match state entries.
    """
    from . import ast as A

    if not isinstance(expr, A.Traversal):
        return None
    out = expr.root
    for op in expr.ops:
        if op[0] == "attr":
            out += f".{op[1]}"
        elif op[0] == "index" and isinstance(op[1], A.Literal):
            v = op[1].value
            out += f'["{v}"]' if isinstance(v, str) else f"[{int(v)}]"
        else:
            return None   # splat / computed index: not a concrete address
    return out


def migrate_state(state: State, module) -> tuple[State, list[tuple[str, str]]]:
    """Honour ``moved {}`` blocks: rename state addresses, no destroy/create.

    Terraform 1.1+ refactoring support — ``moved { from = a.b  to = a.c }``
    retargets existing state so a rename plans as no-op instead of
    destroy+create. Handles whole resources (instance suffixes follow),
    single instances (``from = a.b[1]``), and module renames
    (``from = module.a``). Raises ``ValueError`` when the destination
    already exists in state (terraform: "resource already exists").
    """
    renames: list[tuple[str, str]] = []
    resources = dict(state.resources)
    for blk in getattr(module, "moved", []):
        frm_attr, to_attr = blk.body.attr("from"), blk.body.attr("to")
        frm = _moved_addr(frm_attr.expr) if frm_attr is not None else None
        to = _moved_addr(to_attr.expr) if to_attr is not None else None
        if frm is None or to is None:
            continue
        for addr in list(resources):
            # exact node/instance, an instance of the node, or a child of a
            # moved module — never a mere name prefix (module.a vs module.ab)
            if addr == frm or addr.startswith(frm + "[") or \
                    addr.startswith(frm + "."):
                new = to + addr[len(frm):]
                if new in resources:
                    raise ValueError(
                        f"moved: target {new!r} already exists in state")
                resources[new] = resources.pop(addr)
                renames.append((addr, new))
    if not renames:
        return state, []
    return State(resources=resources, serial=state.serial + 1), renames


def apply_plan(plan: Plan, state: State | None = None) -> State:
    """Advance ``state`` to ``plan``: the simulated ``terraform apply``.

    Computed attributes keep their ``<computed>`` marker in state — the
    simulator has no providers to fill them, and :func:`diff` treats them as
    provider-owned either way. Deleted addresses drop out; the serial bumps
    iff anything changed (terraform's own behaviour for state versioning).
    """
    d = diff(plan, state)
    resources = dict(state.resources) if state else {}
    for addr in d.by_action("delete"):
        resources.pop(addr, None)
    planned = _rendered_instances(plan)
    for addr in d.by_action("create") + d.by_action("update"):
        resources[addr] = planned[addr]
    serial = (state.serial if state else 0) + (0 if d.is_noop else 1)
    return State(resources=resources, serial=serial)
