# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""ISSUE 20 acceptance gates: the durable prefix CDN.

The three-tier content-addressed prefix store (device paged pool →
fleet-shared ``WarmChainStore`` RAM → crash-safe ``DiskChainStore``)
must survive the chaos the serving runbook promises it survives:

- a WHOLE-fleet SIGKILL (every replica process killed for real through
  ``MultiProcTransport``) followed by a cold rebuild comes back with
  the Zipf head warm from disk and bit-matches an undisturbed fleet;
- seeded frame corruption (bitflip / truncation / stale key / foreign
  magic) quarantines LOUDLY with a reason, imports zero corrupt rows,
  and degrades serving to the cold path — never a crash;
- ``disk_spill=None`` (the default) reproduces the stock fleet
  byte-for-byte, and the armed fleet's shared store bills a 1× host
  footprint against the N× private-pool equivalent.
"""

import functools
import os
import signal

import jax
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    MultiProcTransport,
    greedy_decode,
    init_params,
    make_fleet,
)
from nvidia_terraform_modules_tpu.models.hostkv import (
    DiskChainStore,
    WarmChainStore,
)
from nvidia_terraform_modules_tpu.models.serving import make_serve_engine
from nvidia_terraform_modules_tpu.utils.traffic import shared_prefix_prompts

CFG = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
           seq_len=32, batch=2, dtype=jnp.float32)


@functools.lru_cache(maxsize=None)
def _zipf_setup(n=10):
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(2), cfg)
    pairs = shared_prefix_prompts(n, seed=0, n_templates=3,
                                  template_len=8, suffix_lo=1,
                                  suffix_hi=4, vocab=cfg.vocab)
    prompts = tuple(jnp.asarray(p, jnp.int32) for _t, p in pairs)
    max_len = max(int(p.shape[-1]) for p in prompts) + 7
    return cfg, params, prompts, max_len


def _solo(params, prompts, n_new, cfg):
    return [greedy_decode(params, p[None, :], n_new, cfg)[0]
            for p in prompts]


def _assert_all_equal(outs, want, label=""):
    for i, (g, w) in enumerate(zip(outs, want)):
        assert g is not None, f"{label} request {i} unserved"
        assert jnp.array_equal(jnp.asarray(g), w), \
            f"{label} request {i} diverged"


def _frames(spill_dir):
    """Every filed ``.pcd`` frame under the sha-sharded objects tree,
    sorted for determinism."""
    out = []
    objects = os.path.join(spill_dir, "objects")
    for shard in sorted(os.listdir(objects)):
        sdir = os.path.join(objects, shard)
        if os.path.isdir(sdir):
            out.extend(os.path.join(sdir, n)
                       for n in sorted(os.listdir(sdir))
                       if n.endswith(".pcd"))
    return out


# what each seeded corruption kind does to a frame, and the reason the
# quarantine record must carry for it
_CORRUPTIONS = {
    "bitflip": "crc mismatch",
    "truncate_body": "truncated body",
    "truncate_header": "truncated header",
    "stale_key": "stale key",
    "bad_magic": "bad magic",
}


def _corrupt(fpath, kind, donor=None):
    raw = open(fpath, "rb").read()
    if kind == "bitflip":
        buf = bytearray(raw)
        buf[len(buf) - 8] ^= 0x40            # inside the pickled body
        open(fpath, "wb").write(bytes(buf))
    elif kind == "truncate_body":
        open(fpath, "wb").write(raw[:len(raw) // 2])
    elif kind == "truncate_header":
        open(fpath, "wb").write(raw[:6])     # mid-header
    elif kind == "bad_magic":
        open(fpath, "wb").write(b"XXXX" + raw[4:])
    elif kind == "stale_key":
        # a well-formed frame filed under the WRONG chain key (a
        # misplaced backup-restore, a botched rsync): every byte
        # verifies, the identity does not — the record's embedded key
        # must catch it on both the scan and the read path
        open(fpath, "wb").write(open(donor, "rb").read())
    else:                                    # pragma: no cover
        raise AssertionError(kind)


def _cdn_engine(params, cfg, max_len, store):
    return make_serve_engine(params, cfg, max_len=max_len, kv_block=4,
                             share_prefix=True, prefix_keep_blocks=0,
                             shared_store=store)


# ------------------------------------------------- whole-fleet restart


def test_fleet_whole_kill_rebuild_disk_warm_bit_match_tier1(tmp_path):
    """THE ISSUE 20 headline gate. An in-proc fleet writes the Zipf
    head through to the disk tier while serving; a multi-proc fleet
    over the SAME spill dir seeds its real replica processes from the
    restored store and bit-matches; then every replica process is
    SIGKILLed FOR REAL — no drain, no close-publish, exactly a
    machine-room power cut — and a fleet rebuilt cold over the spill
    dir comes back with the head warm from disk (``disk_restored`` >
    0, store hits > 0) and bit-matches the undisturbed baseline. The
    armed fleet also bills the 1× shared-store host footprint against
    the N× private equivalent."""
    cfg, params, prompts, max_len = _zipf_setup()
    want = _solo(params, prompts, 5, cfg)
    spill = str(tmp_path / "cdn")

    fleet = make_fleet(params, cfg, max_len=max_len, replicas=2,
                       kv_block=4, share_prefix=True, disk_spill=spill)
    _assert_all_equal(fleet(prompts, 5, slots=4), want, "armed:")
    cdn = fleet.last_stats["fleet"]["cdn"]
    assert cdn["store"]["disk"]["stored_chains"] > 0
    # host footprint: ONE shared store vs N private pools
    assert cdn["host_bytes_private_equiv"] \
        == 2 * cdn["host_bytes_shared"] > 0

    fl_mp = make_fleet(params, cfg, max_len=max_len, replicas=2,
                       kv_block=4, share_prefix=True, disk_spill=spill,
                       transport=MultiProcTransport(),
                       join_timeout_s=120.0)
    tr = fl_mp.transport
    try:
        _assert_all_equal(fl_mp(prompts, 5, slots=4), want, "multiproc:")
        # the base replicas were seeded from the disk-restored store
        assert fl_mp.last_stats["fleet"]["cdn"]["base_seeded_chains"] > 0
        assert fl_mp.cdn_store.disk_restored > 0
        # the power cut: SIGKILL every replica process, no goodbyes
        pids = [child[0].pid for child in tr._children.values()]
        assert len(pids) == 2
        for pid in pids:
            os.kill(pid, signal.SIGKILL)
        for proc, _chan in list(tr._children.values()):
            proc.join(10.0)
            assert not proc.is_alive()
    finally:
        fl_mp.close()                        # reaps corpses, no raise

    # the rebuild: a cold fleet over the same dir — RAM state died
    # with the processes, the crc-verified disk tail did not
    rebuilt = make_fleet(params, cfg, max_len=max_len, replicas=2,
                         kv_block=4, share_prefix=True,
                         disk_spill=spill)
    assert rebuilt.cdn_store.disk_restored > 0
    _assert_all_equal(rebuilt(prompts, 5, slots=4), want, "rebuilt:")
    store_stats = rebuilt.last_stats["fleet"]["cdn"]["store"]
    assert store_stats["fetch_blocks"] > 0   # admissions hit the CDN
    assert store_stats["disk"]["quarantined"] == 0


def test_fleet_disk_spill_none_reproduces_stock_fleet_tier1(tmp_path):
    """Defaults-off byte-match: ``disk_spill=None`` is the stock fleet
    — no CDN stats record, no store mounted, outputs byte-identical to
    both the armed fleet and solo greedy. The lever must never shift
    tokens; it only changes where warm bytes live."""
    cfg, params, prompts, max_len = _zipf_setup()
    want = _solo(params, prompts, 5, cfg)

    stock = make_fleet(params, cfg, max_len=max_len, replicas=2,
                       kv_block=4, share_prefix=True)
    _assert_all_equal(stock(prompts, 5, slots=4), want, "stock:")
    assert stock.last_stats["fleet"]["cdn"] is None
    assert getattr(stock, "cdn_store", None) is None

    armed = make_fleet(params, cfg, max_len=max_len, replicas=2,
                       kv_block=4, share_prefix=True,
                       disk_spill=str(tmp_path / "cdn"))
    _assert_all_equal(armed(prompts, 5, slots=4), want, "armed:")
    assert armed.last_stats["fleet"]["cdn"] is not None


def test_fleet_disk_spill_validation_is_loud():
    """The lever refuses incoherent wiring up front: a CDN without the
    prefix index has nothing to publish, and explicit host_spill/
    shared_store in engine_kw would fight the tier wiring the lever
    owns."""
    cfg, params, prompts, max_len = _zipf_setup()
    with pytest.raises(ValueError, match="share_prefix"):
        make_fleet(params, cfg, max_len=max_len, replicas=2,
                   kv_block=4, disk_spill="/tmp/x")
    with pytest.raises(ValueError, match="disk_spill owns"):
        make_fleet(params, cfg, max_len=max_len, replicas=2,
                   kv_block=4, share_prefix=True, host_spill=True,
                   disk_spill="/tmp/x")
    with pytest.raises(ValueError, match="cdn_blocks"):
        make_fleet(params, cfg, max_len=max_len, replicas=2,
                   kv_block=4, share_prefix=True, disk_spill="/tmp/x",
                   cdn_blocks=0)


# --------------------------------------------------- seeded corruption


def test_disk_corruption_quarantined_serving_degrades_tier1(tmp_path):
    """The corruption gate, one of each kind: a bitflipped, a
    truncated, and a stale-key frame are ALL quarantined with their
    reasons at restart scan, zero corrupt rows reach any block table,
    and serving over the gutted tier completes bit-exact (cold where
    the chains died, warm where they survived) — never a crash."""
    cfg, params, prompts, max_len = _zipf_setup()
    want = _solo(params, prompts, 5, cfg)
    spill = str(tmp_path / "cdn")

    eng = _cdn_engine(params, cfg, max_len,
                      WarmChainStore(cfg, 32, block_size=4,
                                     disk=DiskChainStore(spill)))
    _assert_all_equal(eng(prompts, 5, slots=4), want, "seed run:")
    frames = _frames(spill)
    assert len(frames) >= 3, "need ≥3 filed chains for the sweep"

    # stale first: its donor (frames[1]) must still be intact
    _corrupt(frames[0], "stale_key", donor=frames[1])
    _corrupt(frames[1], "bitflip")
    _corrupt(frames[2], "truncate_body")

    disk2 = DiskChainStore(spill)
    assert disk2.quarantined == 3
    reasons = " | ".join(disk2.quarantine_reasons)
    assert "crc mismatch" in reasons
    assert "truncated body" in reasons
    assert "stale key" in reasons
    # the quarantine is PHYSICAL: bad frames moved aside, catalog
    # holds only verified survivors
    qdir = os.path.join(spill, "quarantine")
    assert len(os.listdir(qdir)) == 3
    assert disk2.stats()["chains"] == len(frames) - 3

    # serving over the gutted tier: completes, bit-exact, no crash
    eng2 = _cdn_engine(params, cfg, max_len,
                       WarmChainStore(cfg, 32, block_size=4,
                                      disk=disk2))
    _assert_all_equal(eng2(prompts, 5, slots=4), want, "degraded:")


def test_disk_dead_tier_degrades_to_two_tier_path_tier1(tmp_path):
    """An unusable disk root (a FILE where the tier's directory should
    be) kills the whole tier at construction: billed ``degraded``,
    ``dead`` flagged, every put/get a safe no-op — and the engine over
    the two remaining tiers serves bit-exact."""
    cfg, params, prompts, max_len = _zipf_setup()
    want = _solo(params, prompts, 5, cfg)
    hostile = tmp_path / "not-a-dir"
    hostile.write_text("x")

    dead = DiskChainStore(str(hostile))
    assert dead.dead and dead.degraded > 0
    assert dead.put((tuple([1, 2, 3, 4]),), {}) is False
    assert dead.get(b"\x00" * 16) is None

    eng = _cdn_engine(params, cfg, max_len,
                      WarmChainStore(cfg, 32, block_size=4, disk=dead))
    _assert_all_equal(eng(prompts, 5, slots=4), want, "two-tier:")


# --------------------------------------------- the slow sweep matrix


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("tier", ["restore", "fallback"])
@pytest.mark.parametrize("kind", sorted(_CORRUPTIONS))
def test_corruption_matrix_slow(tmp_path, seed, tier, kind):
    """seed × tier × corruption-kind: every kind quarantines with its
    reason on BOTH read paths — the restart scan (``restore``: corrupt
    before construction) and the RAM-miss fallback (``fallback``:
    corrupt after construction, RAM tier cleared so the fetch must
    read the frame) — and serving completes bit-exact either way."""
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(10 + seed), cfg)
    pairs = shared_prefix_prompts(8, seed=seed, n_templates=2,
                                  template_len=8, suffix_lo=1,
                                  suffix_hi=4, vocab=cfg.vocab)
    prompts = tuple(jnp.asarray(p, jnp.int32) for _t, p in pairs)
    max_len = max(int(p.shape[-1]) for p in prompts) + 7
    want = _solo(params, prompts, 5, cfg)
    spill = str(tmp_path / "cdn")

    eng = _cdn_engine(params, cfg, max_len,
                      WarmChainStore(cfg, 32, block_size=4,
                                     disk=DiskChainStore(spill)))
    _assert_all_equal(eng(prompts, 5, slots=4), want, "seed run:")
    frames = _frames(spill)
    assert len(frames) >= 2, "stale_key needs an intact donor frame"
    victim = frames[0]
    leaf = bytes.fromhex(os.path.basename(victim)[:-len(".pcd")])

    if tier == "restore":
        _corrupt(victim, kind, donor=frames[1])
        disk2 = DiskChainStore(spill)
    else:
        disk2 = DiskChainStore(spill)
        _corrupt(victim, kind, donor=frames[1])
        assert disk2.get(leaf) is None   # the read hits the bad frame
    assert disk2.quarantined == 1
    assert _CORRUPTIONS[kind] in " ".join(disk2.quarantine_reasons)

    store = WarmChainStore(cfg, 32, block_size=4, disk=disk2)
    store.clear()                        # force the disk path
    eng2 = _cdn_engine(params, cfg, max_len, store)
    _assert_all_equal(eng2(prompts, 5, slots=4), want,
                      f"{tier}/{kind}:")
