# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""State simulation: apply, persist, re-plan, diff — terraform's checkpoint.

SURVEY §5 maps the reference's checkpoint/resume story onto Terraform state:
"apply is resumable/idempotent; remote state recommended but not configured"
(``/root/reference/README.md:89-91``). The reference cannot test any of that
without a live cloud. This module simulates the state lifecycle offline:

- ``apply_plan`` turns a simulated plan into a :class:`State` (the checkpoint);
- ``State.to_json``/``from_json`` round-trip it (the "remote state" file);
- ``diff`` compares a fresh plan against a prior state the way
  ``terraform plan`` reports actions: create / update / delete / no-op.

Semantics mirror Terraform's: provider-computed attributes (``<computed>``)
never drive updates — only config-driven values do — so a re-plan against an
unchanged module is a full no-op (the idempotence/resume guarantee), while a
changed tfvar surfaces as exactly the updates it causes.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from .plan import Plan, render

COMPUTED_STR = "<computed>"


@dataclasses.dataclass
class State:
    """Applied resource attributes by address — the checkpoint artifact.

    ``outputs`` mirrors the real tfstate shape (``{"name": {"value": …,
    "sensitive": bool}}``): the reference's CNPack workflow reads applied
    outputs with ``terraform output`` and pastes them into the platform
    config (``/root/reference/eks/examples/cnpack/Readme.md:49-94``), so the
    simulator's statefile must carry them too (``tfsim output``).
    """

    resources: dict[str, Any] = dataclasses.field(default_factory=dict)
    serial: int = 0
    outputs: dict[str, dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    # addresses marked for forced recreation (`terraform taint`); cleared
    # by the apply that replaces them
    tainted: set[str] = dataclasses.field(default_factory=set)
    # terraform's lineage: a UUID minted when a statefile is first
    # written and preserved forever after, so two states born from
    # different histories can never be confused for serial-comparable
    # versions of ONE history ("" = legacy statefile, checked nowhere).
    # The CLI mints it at write time (pure functions stay deterministic
    # for golden tests); `state push` refuses a cross-lineage overwrite.
    lineage: str = ""

    def to_json(self) -> str:
        payload = {"serial": self.serial, "resources": self.resources,
                   "outputs": self.outputs}
        if self.tainted:
            payload["tainted"] = sorted(self.tainted)
        if self.lineage:
            payload["lineage"] = self.lineage
        return json.dumps(payload, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "State":
        raw = json.loads(text)
        return cls(resources=raw["resources"], serial=raw["serial"],
                   outputs=raw.get("outputs", {}),
                   tainted=set(raw.get("tainted", [])),
                   lineage=raw.get("lineage", ""))


@dataclasses.dataclass
class Diff:
    """Plan-vs-state actions, terraform-plan style."""

    actions: dict[str, str]               # address → create|update|delete|no-op
    changed_keys: dict[str, list[str]]    # address → keys driving an update

    def by_action(self, action: str) -> list[str]:
        return sorted(a for a, act in self.actions.items() if act == action)

    @property
    def is_noop(self) -> bool:
        return all(a == "no-op" for a in self.actions.values())

    def summary(self) -> str:
        c, u, d = (len(self.by_action(a)) for a in ("create", "update", "delete"))
        r = len(self.by_action("replace"))
        return (f"Plan: {c + r} to add, {u} to change, "
                f"{d + r} to destroy.")


_MISSING = object()   # key present in state but absent from the new plan


def _values_match(planned: Any, applied: Any) -> bool:
    """Deep equality where a planned ``<computed>`` matches anything.

    Terraform only diffs config-driven values; attributes the provider fills
    at apply time cannot cause an update on re-plan. A key *removed* from
    config (``_MISSING``) is a change unless the stored value was itself
    provider-computed.
    """
    if planned is _MISSING:
        return applied == COMPUTED_STR
    if planned == COMPUTED_STR:
        return True
    if isinstance(planned, dict) and isinstance(applied, dict):
        # same missing-key rule at every depth: a key gone from config is
        # only a change if its stored value was config-driven
        return all(_values_match(planned.get(k, _MISSING), applied.get(k))
                   for k in set(planned) | set(applied))
    if isinstance(planned, list) and isinstance(applied, list):
        return len(planned) == len(applied) and all(
            _values_match(p, a) for p, a in zip(planned, applied))
    return planned == applied


def _is_data(addr: str) -> bool:
    """True for data sources at any module depth (module.x.data.t.n too)."""
    while addr.startswith("module."):
        addr = addr.split(".", 2)[2]
    return addr.startswith("data.")


def rendered_instances(plan: Plan) -> dict[str, Any]:
    """Address → rendered attrs for every *tracked* instance of ``plan``.

    Data sources are read every run, never tracked — terraform counts
    neither their reads nor their disappearance as plan actions. Public:
    the stepwise fault-injecting apply (``tfsim/faults/apply.py``) walks
    exactly this map one operation at a time.
    """
    return {addr: render(dict(inst.attrs))
            for addr, inst in plan.instances.items()
            if not _is_data(addr)}


def diff(plan: Plan, state: State | None,
         targets: list[str] | None = None,
         replace: list[str] | None = None) -> Diff:
    """What ``terraform apply`` would do to ``state`` to realise ``plan``.

    With ``targets``, only the targeted instances (plus their dependency
    closure — see :func:`..plan.select_targets`) appear in the diff;
    everything else is left exactly as-is, matching ``terraform plan
    -target``'s surgical scope (including skipping deletes of
    non-targeted state entries). ``replace`` forces recreation of the
    named instances (``terraform plan/apply -replace=ADDR``, the modern
    stateless successor to ``taint``); an address with no instance in
    the plan is an error, matching terraform's refusal.
    """
    from .plan import select_targets

    planned = rendered_instances(plan)
    prior = dict(state.resources) if state else {}
    for addr in replace or []:
        if addr not in planned:
            raise ValueError(
                f"-replace: no resource instance {addr!r} in the plan "
                f"(the address must name a managed instance in the "
                f"current configuration)")
    keep = None
    if targets:
        # universe includes prior-only addresses so a targeted resource
        # whose instance left the config still diffs as a delete
        keep = select_targets(plan, targets,
                              set(planned) | set(prior))
        for addr in replace or []:
            if addr not in keep:
                # terraform: a -replace address the -target scope excludes
                # is an error, not a silent no-op
                raise ValueError(
                    f"-replace: instance {addr!r} is not covered by the "
                    f"given -target selection")
        planned = {a: v for a, v in planned.items() if a in keep}
    actions: dict[str, str] = {}
    changed: dict[str, list[str]] = {}
    for addr, attrs in planned.items():
        if addr not in prior:
            actions[addr] = "create"
            continue
        if (state is not None and addr in state.tainted) or (
                replace and addr in replace):
            # terraform taint / -replace: force recreation regardless of
            # config drift (checked BEFORE the deep attribute compare it
            # would discard)
            actions[addr] = "replace"
            continue
        keys = sorted(
            k for k in set(attrs) | set(prior[addr])
            if not _values_match(attrs.get(k, _MISSING),
                                 prior[addr].get(k)))
        if keys:
            actions[addr] = "update"
            changed[addr] = keys
        else:
            actions[addr] = "no-op"
    for addr in prior:
        if addr not in planned and (keep is None or addr in keep):
            actions[addr] = "delete"
    return Diff(actions=actions, changed_keys=changed)


def _moved_addr(expr) -> str | None:
    """Render a ``moved`` from/to traversal as a state address.

    Unlike ``path_str`` (diagnostics), index ops render their literal keys —
    ``a.b[1]`` / ``a.b["k"]`` — so instance-keyed moves match state entries.
    """
    from . import ast as A

    if not isinstance(expr, A.Traversal):
        return None
    out = expr.root
    for op in expr.ops:
        if op[0] == "attr":
            out += f".{op[1]}"
        elif op[0] == "index" and isinstance(op[1], A.Literal):
            v = op[1].value
            out += f'["{v}"]' if isinstance(v, str) else f"[{int(v)}]"
        else:
            return None   # splat / computed index: not a concrete address
    return out


def _matching_addrs(resources: dict[str, Any], addr: str) -> list[str]:
    """State entries covered by ``addr``: the exact node/instance, instances
    of the node (``addr[...]``), or children of a module (``addr....``) —
    never a mere name prefix (``module.a`` must not match ``module.ab``)."""
    return sorted(a for a in resources
                  if a == addr or a.startswith(addr + "[") or
                  a.startswith(addr + "."))


def _move(resources: dict[str, Any], frm: str, to: str,
          label: str) -> list[tuple[str, str]]:
    """Rename every state entry under ``frm`` to live under ``to``."""
    renames: list[tuple[str, str]] = []
    for addr in _matching_addrs(resources, frm):
        new = to + addr[len(frm):]
        if new in resources:
            raise ValueError(
                f"{label}: target {new!r} already exists in state")
        resources[new] = resources.pop(addr)
        renames.append((addr, new))
    return renames


def migrate_state(state: State, module) -> tuple[State, list[tuple[str, str]]]:
    """Honour ``moved {}`` blocks: rename state addresses, no destroy/create.

    Terraform 1.1+ refactoring support — ``moved { from = a.b  to = a.c }``
    retargets existing state so a rename plans as no-op instead of
    destroy+create. Handles whole resources (instance suffixes follow),
    single instances (``from = a.b[1]``), and module renames
    (``from = module.a``). Raises ``ValueError`` when the destination
    already exists in state (terraform: "resource already exists").
    """
    renames: list[tuple[str, str]] = []
    resources = dict(state.resources)
    for blk in getattr(module, "moved", []):
        frm_attr, to_attr = blk.body.attr("from"), blk.body.attr("to")
        frm = _moved_addr(frm_attr.expr) if frm_attr is not None else None
        to = _moved_addr(to_attr.expr) if to_attr is not None else None
        if frm is None or to is None:
            continue
        renames.extend(_move(resources, frm, to, "moved"))
    if not renames:
        return state, []
    moved = dict(renames)
    return State(resources=resources, serial=state.serial + 1,
                 outputs=state.outputs, lineage=state.lineage,
                 tainted={moved.get(a, a) for a in state.tainted}), renames


def state_rm(state: State, addrs: list[str]) -> tuple[State, list[str]]:
    """``terraform state rm``: forget resources without destroying them.

    The reference *documents this as a required runbook step*: the GKE
    teardown needs ``terraform state rm kubernetes_namespace_v1.gpu-operator``
    before ``destroy`` because the namespace can't be deleted once the
    cluster is gone (``/root/reference/gke/README.md:59``,
    ``/root/reference/gke/examples/cnpack/README.md:27``). Our module designs
    that wart out with destroy ordering (``gke/operator.tf:10-16``), but the
    simulator still ships the verb so the runbook itself is testable.

    Each address may name a resource (all instances follow), one instance,
    or a whole module. Raises ``ValueError`` if an address matches nothing
    (terraform: "Invalid target address").
    """
    resources = dict(state.resources)
    removed: list[str] = []
    for addr in addrs:
        hits = _matching_addrs(resources, addr)
        if not hits:
            raise ValueError(
                f"state rm: no resource in state matches {addr!r}")
        for a in hits:
            del resources[a]
            removed.append(a)
    return State(resources=resources, serial=state.serial + 1,
                 outputs=state.outputs, lineage=state.lineage,
                 tainted=set(state.tainted) - set(removed)), removed


def state_mv(state: State, src: str,
             dst: str) -> tuple[State, list[tuple[str, str]]]:
    """``terraform state mv``: the imperative twin of a ``moved {}`` block.

    Same matching/rename semantics as :func:`migrate_state`, driven from the
    CLI instead of config. Raises ``ValueError`` when ``src`` matches nothing
    or any destination address already exists.
    """
    resources = dict(state.resources)
    renames = _move(resources, src, dst, "state mv")
    if not renames:
        raise ValueError(f"state mv: no resource in state matches {src!r}")
    moved = dict(renames)
    return State(resources=resources, serial=state.serial + 1,
                 outputs=state.outputs, lineage=state.lineage,
                 tainted={moved.get(a, a) for a in state.tainted}), renames


def import_resource(state: State | None, plan: Plan, addr: str,
                    resource_id: str) -> State:
    """``terraform import``: adopt an existing cloud resource into state.

    Terraform 1.x requires a matching configuration block before import;
    the simulator enforces the same and seeds the state entry from the
    planned attributes (the provider would fill the real ones), with the
    operator-supplied ``resource_id`` as ``id`` — so the follow-up plan is
    a no-op, exactly the healthy import-then-plan cycle. Raises
    ``ValueError`` when the address is already tracked or has no
    configuration.
    """
    state = state or State()
    if _is_data(addr):
        raise ValueError(
            f"import: {addr!r} is a data source — data is read every "
            f"plan, never imported (terraform semantics)")
    if addr in state.resources:
        raise ValueError(f"import: {addr!r} already managed in state")
    if addr not in plan.instances:
        instances = sorted(a for a in plan.instances
                           if a.startswith(addr + "["))
        if instances:
            raise ValueError(
                f"import: {addr!r} uses count/for_each — import one "
                f"instance: {', '.join(instances)}")
        raise ValueError(
            f"import: {addr!r} has no configuration block — write the "
            f"resource first (terraform 1.x import semantics)")
    attrs = render(dict(plan.instance(addr).attrs))
    attrs["id"] = resource_id
    resources = dict(state.resources)
    resources[addr] = attrs
    return State(resources=resources, serial=state.serial + 1,
                 outputs=state.outputs, tainted=set(state.tainted),
                 lineage=state.lineage)


def adopt_config_imports(module, plan: Plan, state: State | None, *,
                         collect_missing: bool = False
                         ) -> tuple[State | None, list[tuple[str, str]],
                                    list[tuple[str, str]]]:
    """Honour ``import {}`` blocks (terraform 1.5+ config-driven import).

    Each ``import { to = a.b  id = "…" }`` adopts the named instance into
    state through :func:`import_resource`, making adoption part of the
    reviewed plan instead of an out-of-band CLI step. Idempotent exactly
    like terraform's: a ``to`` already managed is skipped, so the block
    can stay in config after the import lands. ``to`` must be a concrete
    address; ``id`` must be a literal string (tfsim has no evaluation
    context this early, and terraform itself resolves it pre-plan).

    Returns ``(state, adopted, missing_config)``. A target with no
    configuration block errors — unless ``collect_missing``, which
    instead reports it in the third element for
    ``plan -generate-config-out`` to generate a skeleton for.
    """
    from . import ast as A

    adopted: list[tuple[str, str]] = []
    missing: list[tuple[str, str]] = []
    seen: set[str] = set()
    for blk in getattr(module, "imports", []):
        to_attr, id_attr = blk.body.attr("to"), blk.body.attr("id")
        to = _moved_addr(to_attr.expr) if to_attr is not None else None
        if to is None:
            raise ValueError(
                "import block needs a concrete `to` resource address")
        if to in seen:
            # terraform rejects duplicate import targets outright — the
            # already-managed skip below must not silently swallow a
            # second block carrying a DIFFERENT id
            raise ValueError(
                f"duplicate import block for {to}: each resource "
                f"instance can only be imported once")
        seen.add(to)
        id_expr = getattr(id_attr, "expr", None)
        if not (isinstance(id_expr, A.Literal)
                and isinstance(id_expr.value, str)):
            raise ValueError(
                f"import {to}: `id` must be a literal string")
        if state is not None and to in state.resources:
            continue  # already managed: the block is a no-op, not an error
        if _is_data(to):
            # same refusal import_resource gives — checked HERE so the
            # collect_missing branch cannot swallow it into a skeleton
            raise ValueError(
                f"import: {to!r} is a data source — data is read every "
                f"plan, never imported (terraform semantics)")
        if collect_missing and to not in plan.instances and not any(
                a.startswith(to + "[") for a in plan.instances):
            if "[" in to:
                # terraform refuses config generation for count/for_each
                # instances — one block cannot represent an indexed set
                raise ValueError(
                    f"import {to}: config generation is not supported "
                    f"for count/for_each instances — write the resource "
                    f"block by hand")
            missing.append((to, id_expr.value))
            continue
        state = import_resource(state, plan, to, id_expr.value)
        adopted.append((to, id_expr.value))
    return state, adopted, missing


def refresh_state(plan: Plan, state: State | None
                  ) -> tuple[State, list[str], list[str]]:
    """``terraform refresh`` offline: re-render provider-readable facts
    into state WITHOUT applying config changes.

    The simulator has no cloud to poll, so "provider reality" is what the
    plan can re-derive without touching resources: the ``output`` block
    re-evaluated (outputs drift when the block or its inputs changed since
    the last apply) and data sources re-read (they are never stored, so
    re-reading is free). Resource attributes stay untouched — changing
    them is ``apply``'s job. Returns ``(new_state, changed_output_names,
    orphaned_addresses)``; the serial bumps iff outputs changed, and
    orphans (state addresses gone from configuration — the thing a normal
    apply would destroy) are reported, not removed.
    """
    if state is None:
        return State(), [], []
    fresh = {
        name: {"value": render(value),
               "sensitive": name in plan.sensitive_outputs}
        for name, value in plan.outputs.items()
    }
    changed = sorted(
        name for name in set(fresh) | set(state.outputs)
        if fresh.get(name) != state.outputs.get(name))
    orphans = sorted(set(state.resources) - set(rendered_instances(plan)))
    new_state = State(resources=dict(state.resources),
                      serial=state.serial + (1 if changed else 0),
                      outputs=fresh, tainted=set(state.tainted),
                      lineage=state.lineage)
    return new_state, changed, orphans


def apply_plan(plan: Plan, state: State | None = None,
               targets: list[str] | None = None, *,
               d: Diff | None = None) -> State:
    """Advance ``state`` to ``plan``: the simulated ``terraform apply``.

    Computed attributes keep their ``<computed>`` marker in state — the
    simulator has no providers to fill them, and :func:`diff` treats them as
    provider-owned either way. Deleted addresses drop out; the serial bumps
    iff anything changed (terraform's own behaviour for state versioning).
    With ``targets``, only the targeted diff is applied; untargeted state
    entries survive untouched (terraform's ``apply -target``). Pass a
    precomputed ``d`` (for the same plan/state/targets) to skip the second
    diff walk.
    """
    if d is None:
        d = diff(plan, state, targets)
    resources = dict(state.resources) if state else {}
    for addr in d.by_action("delete"):
        resources.pop(addr, None)
    planned = rendered_instances(plan)
    replaced = d.by_action("replace")
    for addr in d.by_action("create") + d.by_action("update") + replaced:
        resources[addr] = planned[addr]
    serial = (state.serial if state else 0) + (0 if d.is_noop else 1)
    # the replace consumed the taint (terraform clears it on recreation)
    tainted = (set(state.tainted) if state else set()) - set(replaced)
    if targets:
        # outputs are evaluated against the FULL plan, which includes
        # untargeted changes that were not applied — recording them would
        # make `tfsim output` claim values the infrastructure doesn't
        # have. Keep the prior outputs; the next full apply refreshes them.
        outputs = dict(state.outputs) if state else {}
    else:
        outputs = {
            name: {"value": render(value),
                   "sensitive": name in plan.sensitive_outputs}
            for name, value in plan.outputs.items()
        }
    return State(resources=resources, serial=serial, outputs=outputs,
                 tainted=tainted,
                 lineage=state.lineage if state else "")
