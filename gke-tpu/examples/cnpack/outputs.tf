# Values the operator pastes into the platform installer config — the same
# handoff shape as the reference's CNPack flow
# (/root/reference/eks/examples/cnpack/Readme.md:49-94), plus the TPU metric
# names GKE exports for the provisioned slice.

output "cluster_name" {
  description = "Name of the TPU cluster."
  value       = module.tpu_cluster.cluster_name
}

output "prometheus_service_account_email" {
  description = "GSA the monitoring KSA impersonates (annotate the KSA with this)."
  value       = google_service_account.prometheus.email
}

output "prometheus_ksa_annotation" {
  description = "Ready-to-paste Workload Identity annotation for the monitoring KSA."
  value       = "iam.gke.io/gcp-service-account: ${google_service_account.prometheus.email}"
}

output "monitoring_namespace" {
  description = "Namespace the monitoring stack must be installed into."
  value       = local.monitoring_namespace
}

output "tpu_slices" {
  description = "Slice facts (selectors, hosts, chips) for scrape-config targeting."
  value       = module.tpu_cluster.tpu_slices
}

output "tpu_metric_types" {
  description = "GKE system metrics exported for TPU nodes; use in dashboards/alerts."
  value = [
    "kubernetes.io/node/accelerator/duty_cycle",
    "kubernetes.io/node/accelerator/memory_used",
    "kubernetes.io/node/accelerator/memory_total",
    "kubernetes.io/container/accelerator/tensorcore_utilization",
  ]
}

output "ca_pool" {
  description = "CAS pool the GoogleCASClusterIssuer must reference (null when private_ca_enabled = false)."
  value       = var.private_ca_enabled ? google_privateca_ca_pool.cnpack[0].name : null
}

output "ca_resource_name" {
  description = "Fully-qualified root CA resource (paste into the issuer spec)."
  value       = var.private_ca_enabled ? google_privateca_certificate_authority.cnpack[0].id : null
}

output "cas_issuer_service_account_email" {
  description = "GSA the cert-manager google-cas-issuer KSA impersonates."
  value       = var.private_ca_enabled ? google_service_account.cas_issuer[0].email : null
}

output "fluentbit_service_account_email" {
  description = "GSA the Fluent Bit DaemonSet KSA impersonates."
  value       = var.fluentbit_enabled ? google_service_account.fluentbit[0].email : null
}

output "log_bucket" {
  description = "Dedicated Cloud Logging bucket receiving cluster logs."
  value       = var.fluentbit_enabled ? google_logging_project_bucket_config.cnpack[0].bucket_id : null
}
