# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""TPU-semantic lint rules.

The class of mistake these catch is the expensive one: a slice declaration
whose (version, topology) pair the TPU control plane will reject — or
accept and then never schedule — surfaces today only hours into a real
``terraform apply``. The rules cross-check every statically-visible slice
declaration (``tpu_slices`` in module calls, tfvars files, and variable
defaults) and every literal TPU node pool against the vendored generation
facts in :mod:`tpu_facts`.
"""

from __future__ import annotations

import dataclasses
import re

from .. import ast as A
from . import tpu_facts as T
from .engine import Finding, LintContext, rule


@dataclasses.dataclass
class SliceDecl:
    """One statically-visible TPU slice declaration."""

    fname: str
    line: int
    name: str
    version: object        # resolved literal or None
    topology: object
    prefer_single_host: object
    origin: str            # "tfvars" | "module call" | "variable default"
    spot: object = None    # resolved literal or None
    queued: object = None  # queued_provisioning, resolved literal or None


def _object_items(expr):
    if isinstance(expr, A.ObjectExpr):
        for item in expr.items:
            if isinstance(item.key, A.Literal):
                yield str(item.key.value), item.value, item
    return


def _optional_defaults(var) -> dict:
    """Per-field ``optional(type, default)`` literals from a variable's
    ``map(object({…}))`` type. The shipped module declares slice shape
    exactly this way — an entry ``{}`` inherits ``version = "v5e"``,
    ``topology = "2x4"`` from the type, so those defaults must be
    checkable too, not a blind spot."""
    if var is None:
        return {}
    e = var.type_expr
    while isinstance(e, A.Call) and e.name in ("map", "list", "set") \
            and e.args:
        e = e.args[0]
    if not (isinstance(e, A.Call) and e.name == "object" and e.args):
        return {}
    out = {}
    for name, value, _ in _object_items(e.args[0]):
        if isinstance(value, A.Call) and value.name == "optional" and \
                len(value.args) == 2 and isinstance(value.args[1], A.Literal):
            out[name] = value.args[1].value
    return out


def _decls_from_object(ctx, fname, expr, origin, defaults=None):
    defaults = defaults or {}

    def field(fields, key):
        # an absent field inherits the variable type's optional() default;
        # a present-but-unresolvable one (e.g. a var reference) stays None
        if key not in fields:
            return defaults.get(key)
        return ctx.resolve_literal(fields[key])

    out = []
    for name, value, item in _object_items(expr):
        fields = {k: v for k, v, _ in _object_items(value)}
        if not isinstance(value, A.ObjectExpr):
            continue
        out.append(SliceDecl(
            fname=fname,
            line=item.line or value.line,
            name=name,
            version=field(fields, "version"),
            topology=field(fields, "topology"),
            prefer_single_host=field(fields, "prefer_single_host"),
            origin=origin,
            spot=field(fields, "spot"),
            queued=field(fields, "queued_provisioning"),
        ))
    return out


def slice_declarations(ctx: LintContext) -> list[SliceDecl]:
    """Every ``tpu_slices = { … }`` object the linter can see statically:
    module-call arguments, tfvars(.example) files, and the declaring
    variable's own default. The flat view over :func:`_slice_containers`
    — ONE traversal serves both the per-slice rules and the
    per-container elasticity rule."""
    if getattr(ctx, "_slice_decls", None) is not None:
        return ctx._slice_decls
    decls = [d for _fname, _nap, ds, _origin in _slice_containers(ctx)
             for d in ds]
    ctx._slice_decls = decls
    return decls


@rule("tpu-unknown-version", severity="error", family="tpu",
      summary="tpu_slices entry names a TPU generation that does not exist")
def check_unknown_version(ctx: LintContext):
    for d in slice_declarations(ctx):
        if isinstance(d.version, str) and d.version not in T.GENERATIONS:
            yield (f"{d.fname}:{d.line}",
                   f"tpu_slices[{d.name!r}] ({d.origin}): version "
                   f"{d.version!r} is not a known TPU generation "
                   f"(known: {', '.join(T.GENERATIONS)})")


@rule("tpu-invalid-topology", severity="error", family="tpu",
      summary="(version, topology) pair is not a provisionable TPU slice")
def check_invalid_topology(ctx: LintContext):
    for d in slice_declarations(ctx):
        if not isinstance(d.version, str) or not isinstance(d.topology, str):
            continue
        if d.version not in T.GENERATIONS:
            continue  # tpu-unknown-version owns that finding
        err = T.topology_error(d.version, d.topology)
        if err:
            yield (f"{d.fname}:{d.line}",
                   f"tpu_slices[{d.name!r}] ({d.origin}): {err}")


@rule("tpu-singlehost-packing", severity="warning", family="tpu",
      summary="prefer_single_host set where it cannot take effect")
def check_singlehost_packing(ctx: LintContext):
    for d in slice_declarations(ctx):
        if d.prefer_single_host is not True:
            continue
        if not isinstance(d.version, str) or d.version not in T.GENERATIONS:
            continue
        where = f"{d.fname}:{d.line}"
        if d.version not in T.SINGLE_HOST_PACK:
            yield (where,
                   f"tpu_slices[{d.name!r}] ({d.origin}): "
                   f"prefer_single_host has no effect on {d.version} — "
                   f"pod slices are always "
                   f"{T.CHIPS_PER_HOST[d.version]} chips per host")
            continue
        if not isinstance(d.topology, str):
            continue
        chips = T.chips_of(d.topology)
        if chips is not None and chips != 8:
            yield (where,
                   f"tpu_slices[{d.name!r}] ({d.origin}): "
                   f"prefer_single_host has no effect on a {chips}-chip "
                   f"topology — only 8-chip {d.version} slices can pack "
                   f"onto one {T.MACHINE_PREFIX[d.version]}-8t host")


@rule("tpu-generation-facts", severity="error", family="tpu",
      summary="a tpu_generations fact table disagrees with the vendored "
              "TPU facts")
def check_generation_facts(ctx: LintContext):
    """The module's own per-generation table is config too: a typo'd
    node selector or machine prefix provisions pools no workload ever
    schedules onto."""
    expected = {
        "node_selector": T.NODE_SELECTOR,
        "machine": T.MACHINE_PREFIX,
        "chips_per_host": T.CHIPS_PER_HOST,
    }
    for fname, body in ctx.mod.files.items():
        for blk in body.blocks:
            if blk.type != "locals":
                continue
            attr = blk.body.attr("tpu_generations")
            if attr is None or not isinstance(attr.expr, A.ObjectExpr):
                continue
            for gen, value, item in _object_items(attr.expr):
                where = f"{fname}:{item.line or attr.line}"
                if gen not in T.GENERATIONS:
                    yield (where,
                           f"tpu_generations[{gen!r}]: not a known TPU "
                           f"generation (known: {', '.join(T.GENERATIONS)})")
                    continue
                for key, fvalue, fitem in _object_items(value):
                    want = expected.get(key, {}).get(gen)
                    if want is None:
                        continue
                    got = ctx.resolve_literal(fvalue)
                    if got is not None and got != want:
                        yield (f"{fname}:{fitem.line or item.line}",
                               f"tpu_generations[{gen!r}].{key} is "
                               f"{got!r}, but {gen} uses {want!r}")


def _literal(ctx, attr):
    return None if attr is None else ctx.resolve_literal(attr.expr)


def _placement_blocks(body):
    """placement_policy blocks, static or dynamic."""
    out = []
    for b in body.blocks:
        if b.type == "placement_policy":
            out.append((b, b.body))
        elif b.type == "dynamic" and b.labels and \
                b.labels[0] == "placement_policy":
            for content in b.body.blocks_of("content"):
                out.append((b, content.body))
            if not b.body.blocks_of("content"):
                out.append((b, None))
    return out


@rule("tpu-chip-arithmetic", severity="error", family="tpu",
      summary="node pool host/chip arithmetic does not factor "
              "(node_count × machine suffix ≠ topology chips)")
def check_pool_arithmetic(ctx: LintContext):
    for r in ctx.mod.resources.values():
        if r.type != "google_container_node_pool":
            continue
        ncs = r.body.blocks_of("node_config")
        if not ncs:
            continue
        mt = _literal(ctx, ncs[0].body.attr("machine_type"))
        if not isinstance(mt, str):
            continue
        parsed = T.parse_machine_type(mt)
        if parsed is None:
            continue
        gen, host_chips = parsed
        where = f"{r.file}:{r.line}"
        if not T.valid_host_chips(gen, host_chips):
            ok = (T.SINGLE_HOST_PACK.get(gen)
                  or (T.CHIPS_PER_HOST[gen],))
            yield (where,
                   f"{r.address}: machine type {mt!r} packs {host_chips} "
                   f"chips on a host, but {gen} hosts carry "
                   f"{', '.join(str(c) for c in ok)}")
            continue
        # topology from an attached placement policy, when literal
        topology = None
        for _blk, pbody in _placement_blocks(r.body):
            if pbody is not None:
                topology = _literal(ctx, pbody.attr("tpu_topology")) \
                    or topology
        if not isinstance(topology, str):
            continue
        if T.topology_error(gen, topology):
            yield (where,
                   f"{r.address}: placement_policy.tpu_topology "
                   f"{topology!r}: {T.topology_error(gen, topology)}")
            continue
        chips = T.chips_of(topology)
        if chips and gen in T.SINGLE_HOST_PACK and \
                chips > host_chips and host_chips != T.CHIPS_PER_HOST[gen]:
            yield (where,
                   f"{r.address}: machine type {mt!r} is single-host "
                   f"packing, but topology {topology!r} is {chips} chips "
                   f"— multi-host {gen} slices use "
                   f"{T.MACHINE_PREFIX[gen]}-{T.CHIPS_PER_HOST[gen]}t")
            continue
        node_count = _literal(ctx, r.body.attr("node_count"))
        if chips and isinstance(node_count, int):
            hosts = max(1, chips // host_chips)
            if node_count != hosts:
                yield (where,
                       f"{r.address}: node_count = {node_count}, but "
                       f"topology {topology!r} on {host_chips}-chip "
                       f"{mt!r} hosts is exactly {hosts} host(s) — a "
                       f"slice is atomic, the pool must match it")


def _spot_tpu_pools(ctx: LintContext):
    """``(resource, "spot"|"preemptible")`` for every node pool that
    statically opts into preemptible TPU capacity."""
    for r in ctx.mod.resources.values():
        if r.type != "google_container_node_pool":
            continue
        ncs = r.body.blocks_of("node_config")
        if not ncs:
            continue
        spot = _literal(ctx, ncs[0].body.attr("spot"))
        preemptible = _literal(ctx, ncs[0].body.attr("preemptible"))
        if spot is not True and preemptible is not True:
            continue
        mt = _literal(ctx, ncs[0].body.attr("machine_type"))
        is_tpu = isinstance(mt, str) and T.parse_machine_type(mt) is not None
        if not is_tpu:
            # a COMPACT policy with tpu_topology marks a TPU pool even
            # when the machine type is not statically resolvable
            is_tpu = any(
                pbody is not None and pbody.attr("tpu_topology") is not None
                for _blk, pbody in _placement_blocks(r.body))
        if is_tpu:
            yield r, ("spot" if spot is True else "preemptible")


@rule("tpu-spot-no-recovery", severity="warning", family="tpu",
      summary="spot/preemptible TPU pool with no timeouts block or "
              "lifecycle guard")
def check_spot_no_recovery(ctx: LintContext):
    """Preemptible TPU capacity is exactly where mid-apply faults land:
    a spot slice can be reclaimed while the pool is still creating, and
    the retry loop then runs until the operation's ``timeouts`` budget —
    the *provider default* budget if the config declares none, which is
    rarely what an operator sizing for TPU stockout churn wants. A pool
    that opts into preemptible capacity without a ``timeouts {}`` block
    or a ``lifecycle {}`` guard (``create_before_destroy`` keeps serving
    capacity while the replacement assembles) has no recovery posture at
    all. (The *workload*-side counterpart is ``tpu-spot-no-grace``: the
    pods on these pools need a termination grace period big enough for
    the emergency-checkpoint drain.)"""
    for r, flag in _spot_tpu_pools(ctx):
        if r.body.blocks_of("timeouts") or r.body.blocks_of("lifecycle"):
            continue
        yield (f"{r.file}:{r.line}",
               f"{r.address}: {flag} TPU capacity with no timeouts block "
               f"or lifecycle guard — preemption lands mid-apply; declare "
               f"timeouts {{ create/delete }} sized to your capacity "
               f"churn (and consider lifecycle.create_before_destroy) so "
               f"an interrupted apply resumes instead of wedging")


# the kubernetes workload types carrying a pod template (hops from the
# resource's spec block down to the POD spec), plus the bare pod
_POD_TEMPLATE_TYPES = {
    "kubernetes_job_v1": ("template",),
    "kubernetes_cron_job_v1": ("job_template", "template"),
    "kubernetes_deployment_v1": ("template",),
    "kubernetes_stateful_set_v1": ("template",),
    "kubernetes_daemon_set_v1": ("template",),
    "kubernetes_pod_v1": (),
}

# the floor for spot TPU workloads: kubernetes' default 30s equals the
# default emergency-checkpoint budget (ResilienceConfig.grace_seconds)
# with ZERO headroom for the drain itself — require real headroom
_GRACE_FLOOR_S = 60


def _pod_specs(r):
    for spec in r.body.blocks_of("spec"):
        body = spec.body
        for hop in _POD_TEMPLATE_TYPES[r.type]:
            tmpl = body.blocks_of(hop)
            if not tmpl:
                body = None
                break
            inner = tmpl[0].body.blocks_of("spec")
            if not inner:
                body = None
                break
            body = inner[0].body
        if body is not None:
            yield body


def _schedules_on_tpu(ctx: LintContext, pod) -> bool:
    sel = pod.attr("node_selector")
    if sel is not None and isinstance(sel.expr, A.ObjectExpr):
        for key, _value, _item in _object_items(sel.expr):
            if key.startswith("cloud.google.com/gke-tpu"):
                return True
    for tol in pod.blocks_of("toleration"):
        if _literal(ctx, tol.body.attr("key")) == "google.com/tpu":
            return True
    for c in pod.blocks_of("container"):
        for res in c.body.blocks_of("resources"):
            for which in ("requests", "limits"):
                a = res.body.attr(which)
                if a is not None and isinstance(a.expr, A.ObjectExpr):
                    for key, _value, _item in _object_items(a.expr):
                        if key == "google.com/tpu":
                            return True
    return False


@rule("tpu-spot-no-grace", severity="warning", family="tpu",
      summary="TPU workload on spot capacity without a termination "
              "grace period covering the emergency-checkpoint budget")
def check_spot_no_grace(ctx: LintContext):
    """The pool-side recovery posture (``tpu-spot-no-recovery``) has a
    workload-side twin: when a spot slice is reclaimed, Kubernetes
    SIGTERMs every pod and waits ``termination_grace_period_seconds``
    (default **30s**) before SIGKILL. The supervised train loop
    (``models/resilience.py``) uses that window to drain the in-flight
    step and commit an emergency checkpoint — 30s is exactly the default
    emergency budget (``TPU_SMOKETEST_GRACE_SECONDS``) with zero drain
    headroom, so a pod spec that leaves the default (or sets less than
    ~2× the budget) loses the step it was promised to keep. Fires only
    when the module statically provisions spot/preemptible TPU capacity
    AND a kubernetes workload schedules onto TPU nodes. (For *multislice*
    spot fleets the fleet-level twin is ``tpu-multislice-no-elastic``:
    grace saves the step, an autoscaler range saves the fleet — for
    *serving* pools the twin is ``tpu-spot-serving-no-headroom``: grace
    saves the step, failover headroom saves the traffic — and
    ``tpu-no-monitoring`` is the observability leg: the same spot churn
    that makes grace mandatory makes its incidents undiagnosable
    without a metrics pipeline.)"""
    spot_origin = None
    for r, flag in _spot_tpu_pools(ctx):
        spot_origin = f"{r.address} ({flag})"
        break
    if spot_origin is None:
        for d in slice_declarations(ctx):
            if d.spot is True:
                spot_origin = f"tpu_slices[{d.name!r}] ({d.origin}, spot)"
                break
    if spot_origin is None:
        return
    for r in ctx.mod.resources.values():
        if r.type not in _POD_TEMPLATE_TYPES:
            continue
        for pod in _pod_specs(r):
            if not _schedules_on_tpu(ctx, pod):
                continue
            attr = pod.attr("termination_grace_period_seconds")
            if attr is None:
                yield (f"{r.file}:{r.line}",
                       f"{r.address}: schedules onto TPU nodes while "
                       f"{spot_origin} provisions preemptible capacity, "
                       f"but declares no termination_grace_period_seconds "
                       f"— the kubernetes default (30s) equals the "
                       f"default emergency-checkpoint budget with no "
                       f"drain headroom; set >= {_GRACE_FLOOR_S}s, above "
                       f"TPU_SMOKETEST_GRACE_SECONDS")
                continue
            grace = ctx.resolve_literal(attr.expr)
            if isinstance(grace, (int, float)) and grace < _GRACE_FLOOR_S:
                yield (f"{r.file}:{attr.line or r.line}",
                       f"{r.address}: termination_grace_period_seconds = "
                       f"{grace:g} is below the {_GRACE_FLOOR_S}s floor "
                       f"for spot TPU workloads ({spot_origin}) — the "
                       f"SIGTERM drain plus the emergency checkpoint "
                       f"(TPU_SMOKETEST_GRACE_SECONDS, default 30s) "
                       f"needs the full window")


# naming/label tokens that mark a node pool as SERVING-shaped — the
# fleet router's capacity, where a preempted node means live traffic
# has to fail over NOW, not a training step to resume later
_SERVING_TOKENS = ("serve", "serving", "inference", "infer")


def _serving_shaped(ctx: LintContext, r) -> str | None:
    """The evidence a pool is serving-shaped, or None: a serving token
    in its terraform name, its ``name`` attribute, or a ``node_config``
    label key/value (``role = "serving"`` and friends)."""
    hay = [r.name]
    lit = _literal(ctx, r.body.attr("name"))
    if isinstance(lit, str):
        hay.append(lit)
    for nc in r.body.blocks_of("node_config"):
        la = nc.body.attr("labels")
        if la is not None and isinstance(la.expr, A.ObjectExpr):
            for key, value, _item in _object_items(la.expr):
                hay.append(key)
                v = ctx.resolve_literal(value)
                if isinstance(v, str):
                    hay.append(v)
    for h in hay:
        # whole-token match, not substring: "reserved"/"preserve"
        # contain "serve" but are not serving-shaped names
        toks = re.split(r"[^a-z0-9]+", h.lower())
        if any(t in _SERVING_TOKENS for t in toks):
            return h
    return None


@rule("tpu-spot-serving-no-headroom", severity="warning", family="tpu",
      summary="serving-shaped spot TPU pool with max_count == "
              "min_count — no failover headroom when a replica is "
              "reclaimed")
def check_spot_serving_no_headroom(ctx: LintContext):
    """The SERVING leg of the spot posture tripod
    (``tpu-spot-no-grace`` saves the training *step*,
    ``tpu-multislice-no-elastic`` saves the training *fleet* — this
    rule saves the *traffic*). The serving fault plane
    (``models/fleet.py``) survives a reclaimed replica by redriving
    its requests to survivors and re-shedding against the SURVIVING
    capacity — correctness is kept, but goodput drops to N−1 and stays
    there until the infrastructure replaces the node. A serving-shaped
    pool (``serve``/``inference`` in its name or node labels) on spot
    capacity whose autoscaler range is pinned — ``max_node_count ==
    min_node_count``, or no ``autoscaling`` block at all — has no
    failover headroom: every preemption is a permanent capacity loss
    the runtime can only answer with load shedding
    (``fleet_shed_total`` rises, the ``fleet_degraded`` span never
    closes). Give the autoscaler room above the floor so reclaimed
    serving capacity comes back without a human apply. (The sibling
    sizing rule for serving pools is ``tpu-serving-no-host-ram``:
    headroom saves the traffic when a NODE dies, host RAM saves the
    prefix working set when the HBM pool is the bottleneck. The
    INVERSE rule is ``tpu-serving-autoscaler-unused``: headroom that
    exists but that no workload consumes is spend, not safety.)"""
    for r, flag in _spot_tpu_pools(ctx):
        shaped = _serving_shaped(ctx, r)
        if shaped is None:
            continue
        where = f"{r.file}:{r.line}"
        autos = [b for b in _named_blocks(r.body, "autoscaling")
                 if b is not None]
        if not autos:
            yield (where,
                   f"{r.address}: serving-shaped ({shaped!r}) {flag} "
                   f"TPU pool with no autoscaling block — the node "
                   f"count is pinned, so a reclaimed node is a "
                   f"permanent capacity loss the fleet router can only "
                   f"shed against (degraded mode, fleet_replica_down/"
                   f"fleet_shed_total); declare autoscaling with "
                   f"max_node_count above min_node_count so failover "
                   f"capacity comes back without a human apply (the "
                   f"workload-side twin of tpu-spot-no-grace)")
            continue
        for b in autos:
            for lo_k, hi_k in (
                    ("min_node_count", "max_node_count"),
                    ("total_min_node_count", "total_max_node_count")):
                lo = _literal(ctx, b.attr(lo_k))
                hi = _literal(ctx, b.attr(hi_k))
                if isinstance(lo, (int, float)) \
                        and isinstance(hi, (int, float)) and lo == hi:
                    yield (where,
                           f"{r.address}: serving-shaped ({shaped!r}) "
                           f"{flag} TPU pool pins {hi_k} == {lo_k} "
                           f"({lo:g}) — no failover headroom: a "
                           f"reclaimed node leaves the serving fleet "
                           f"at N−1 with nothing to grow back into, "
                           f"and the runtime's only lever is load "
                           f"shedding; set {hi_k} above {lo_k} (the "
                           f"serving twin of tpu-spot-no-grace's "
                           f"drain-budget posture)")


# identifier shapes that mark the tiered-KV host-spill lever as wired
# into a deployment: the serve engine's own knobs (host_spill= /
# host_blocks= on make_serve_engine) and the env-var spellings a pod
# spec would carry them through
_HOST_SPILL_RE = re.compile(
    r"host[_-]?spill|host[_-]?blocks|kv[_-]?spill", re.IGNORECASE)


def _host_spill_wiring(ctx: LintContext) -> str | None:
    """The first evidence that this module wires the tiered-KV host
    spill into its workloads, or None: a ``host_spill``/``host_blocks``
    -style variable in the module API, a module-call argument of that
    shape, or a pod env var carrying the knob to the runtime."""
    for name, v in ctx.mod.variables.items():
        if _HOST_SPILL_RE.search(name):
            return f'variable "{name}"'
    for mc in ctx.mod.module_calls.values():
        for a in mc.body.attributes:
            if _HOST_SPILL_RE.search(a.name):
                return f'module "{mc.name}" argument "{a.name}"'
    for r in ctx.mod.resources.values():
        for node in A.walk(r.body):
            if not (isinstance(node, A.Block) and node.type == "env"):
                continue
            na = node.body.attr("name")
            val = ctx.resolve_literal(na.expr) if na is not None else None
            if isinstance(val, str) and _HOST_SPILL_RE.search(val):
                return f'{r.address} env "{val}"'
    return None


@rule("tpu-serving-no-host-ram", severity="warning", family="tpu",
      summary="serving pool wires the tiered-KV host spill but its "
              "machine type's host RAM is the family minimum — "
              "nothing to spill into")
def check_serving_no_host_ram(ctx: LintContext):
    """The SIZING leg of the serving posture
    (``tpu-spot-serving-no-headroom`` saves the traffic when a NODE
    dies — this rule saves the prefix working set when HBM is the
    bottleneck). The tiered KV cache (``models/hostkv.py``,
    ``host_spill=`` on the serve engine) turns HBM into a cache over a
    HOST-RAM-sized prefix index: its whole premise is that a TPU host
    carries an order of magnitude more RAM than HBM (a v5e-4t host:
    192 GB of RAM next to 64 GB of HBM). The 1-chip single-host
    machines are the family's host-RAM FLOOR (``ct5lp-hightpu-1t``:
    48 GB, ``ct6e-standard-1t``: 44 GB) — after the runtime, weights
    staging and the OS, there is almost nothing left for
    ``host_blocks``, so a spill tier wired onto such a pool thrashes
    (``prefix_swapin_ms`` rises, ``prefix_host_hit_frac`` stays low —
    see the "Tiered KV cache runbook" in ``gke-tpu/README.md`` for
    the sizing arithmetic) or OOMs the host. Fires only when BOTH
    sides are statically visible: a serving-shaped TPU pool on a
    floor-class machine AND host-spill wiring (a ``host_spill``/
    ``host_blocks``-style variable, module argument, or pod env var)
    in the same module. (The DURABILITY leg of the same posture is
    ``tpu-serving-no-durable-prefix``: this rule sizes the RAM tier,
    that one makes sure its disk tail survives a fleet restart.)"""
    wiring = _host_spill_wiring(ctx)
    if wiring is None:
        return
    for r in ctx.mod.resources.values():
        if r.type != "google_container_node_pool":
            continue
        shaped = _serving_shaped(ctx, r)
        if shaped is None:
            continue
        ncs = r.body.blocks_of("node_config")
        if not ncs:
            continue
        mt = _literal(ctx, ncs[0].body.attr("machine_type"))
        if not isinstance(mt, str):
            continue
        parsed = T.parse_machine_type(mt)
        if parsed is None:
            continue
        gen, chips = parsed
        if not T.host_memory_is_family_floor(gen, chips):
            continue
        gb = T.host_memory_gb(gen, chips)
        biggest = max(
            (b for (g, _c), b in T.HOST_MEMORY_GB.items() if g == gen))
        yield (f"{r.file}:{r.line}",
               f"{r.address}: serving-shaped ({shaped!r}) pool wires "
               f"the tiered-KV host spill ({wiring}) onto "
               f"{mt} — {gb} GB of host RAM is {gen}'s family "
               f"minimum, so the spill tier has almost nothing to "
               f"grow into after the runtime's own footprint; use a "
               f"larger host class (up to {biggest} GB on {gen}) or "
               f"drop host_spill on this pool (watch "
               f"prefix_swapin_ms / prefix_host_hit_frac — the "
               f"sizing arithmetic is in the gke-tpu README's tiered-"
               f"KV runbook; the failover twin is "
               f"tpu-spot-serving-no-headroom)")


# identifier shapes that mark a DURABLE home for the prefix CDN's disk
# tail as provisioned: the runtime's own knob (disk_spill= on
# make_fleet), a prefix-cache bucket/volume variable, or the local-ssd
# spellings GKE uses for node-attached NVMe
_DURABLE_PREFIX_RE = re.compile(
    r"disk[_-]?spill|prefix[_-]?(cache|cdn)|durable|"
    r"(cache|spill)[_-]?(bucket|dir|path|volume)|local[_-]?ssd",
    re.IGNORECASE)
# node_config blocks that attach local SSD to the pool itself —
# durable across pod restarts, which is the tier's survival domain
_LOCAL_SSD_BLOCKS = ("ephemeral_storage_local_ssd_config",
                     "local_nvme_ssd_block_config")


def _durable_prefix_evidence(ctx: LintContext, r) -> str | None:
    """The first evidence this module gives the prefix CDN's disk tail
    somewhere durable to live, or None: a ``disk_spill``/
    ``prefix_cache``-style variable, module argument, or pod env var;
    a storage bucket resource; or local SSD attached to the pool
    ``r`` itself."""
    for nc in r.body.blocks_of("node_config"):
        if nc.body.attr("local_ssd_count") is not None:
            return f"{r.address} local_ssd_count"
        for bt in _LOCAL_SSD_BLOCKS:
            if nc.body.blocks_of(bt):
                return f"{r.address} {bt}"
    for res in ctx.mod.resources.values():
        if res.type == "google_storage_bucket":
            return res.address
    for name in ctx.mod.variables:
        if _DURABLE_PREFIX_RE.search(name):
            return f'variable "{name}"'
    for mc in ctx.mod.module_calls.values():
        for a in mc.body.attributes:
            if _DURABLE_PREFIX_RE.search(a.name):
                return f'module "{mc.name}" argument "{a.name}"'
    for res in ctx.mod.resources.values():
        for node in A.walk(res.body):
            if not (isinstance(node, A.Block) and node.type == "env"):
                continue
            na = node.body.attr("name")
            val = ctx.resolve_literal(na.expr) if na is not None else None
            if isinstance(val, str) and _DURABLE_PREFIX_RE.search(val):
                return f'{res.address} env "{val}"'
    return None


@rule("tpu-serving-no-durable-prefix", severity="warning", family="tpu",
      summary="serving pool wires the host-spill prefix tier but "
              "provisions nothing durable for its disk tail — the "
              "prefix working set dies with the fleet")
def check_serving_no_durable_prefix(ctx: LintContext):
    """The DURABILITY leg of the serving posture
    (``tpu-spot-serving-no-headroom`` saves the traffic,
    ``tpu-serving-no-host-ram`` saves the working set while the fleet
    is UP — this rule saves it across a fleet-wide restart). The
    prefix CDN's host tier (``models/hostkv.py``, ``host_spill=``) is
    RAM: a node-pool upgrade, a zone drain, or a full fleet crash
    vaporizes the entire Zipf head of shared-template prefixes, and
    every user pays cold prefill again. The runtime's crash-safe disk
    tail (``disk_spill=`` → ``DiskChainStore``) exists for exactly
    this, but it needs a DURABLE home: node-attached local SSD, a
    mounted volume, or a GCS bucket. Fires when a serving-shaped TPU
    pool has host-spill wiring statically visible but the module
    provisions no durable evidence (a ``disk_spill``/``prefix_cache``
    -style variable, module argument, or pod env var; local SSD on the
    pool; a storage bucket) — see the "Prefix CDN runbook" in
    ``gke-tpu/README.md`` for tiers and degradation modes."""
    wiring = _host_spill_wiring(ctx)
    if wiring is None:
        return
    for r in ctx.mod.resources.values():
        if r.type != "google_container_node_pool":
            continue
        shaped = _serving_shaped(ctx, r)
        if shaped is None:
            continue
        ncs = r.body.blocks_of("node_config")
        if not ncs:
            continue
        mt = _literal(ctx, ncs[0].body.attr("machine_type"))
        if not isinstance(mt, str) or T.parse_machine_type(mt) is None:
            continue
        if _durable_prefix_evidence(ctx, r) is not None:
            continue
        yield (f"{r.file}:{r.line}",
               f"{r.address}: serving-shaped ({shaped!r}) pool wires "
               f"the host-spill prefix tier ({wiring}) with no "
               f"durable home for its disk tail — host RAM dies with "
               f"the fleet, so a full restart cold-starts every "
               f"shared-template prefix; attach local SSD "
               f"(local_ssd_count / ephemeral_storage_local_ssd_"
               f"config), mount a volume, or point disk_spill at a "
               f"bucket-backed path (prefix_disk_hit_frac shows the "
               f"tail working; the RAM-sizing twin is "
               f"tpu-serving-no-host-ram)")


# identifier shapes that mark the serving runtime's ELASTIC control
# loop as wired into a deployment: the fleet's own knobs (autoscale= /
# min_replicas / max_replicas on make_fleet's AutoscalePolicy) and the
# env-var spellings a pod spec would carry them through. Deliberately
# NOT a bare "autoscal" prefix: a variable like
# "autoscaling_max_node_count" that only parameterizes the pool's own
# autoscaling block is the INFRA side of the range — counting it as
# runtime wiring would silence the rule on exactly the
# declared-but-unconsumed modules it targets ("autoscaling" has no
# 'e', so the plain "autoscale" spelling — the runtime knob's — can
# never match it, while autoscale_policy / FLEET_AUTOSCALE_ENABLED do)
_AUTOSCALE_RE = re.compile(
    r"autoscale|(min|max)[_-]?replicas|replica[_-]?(min|max)|"
    r"fleet[_-]?(min|max|size)", re.IGNORECASE)


def _autoscale_wiring(ctx: LintContext) -> str | None:
    """The first evidence that this module wires the serving
    autoscaler's bounds into its workloads, or None: an ``autoscale``/
    ``min_replicas``/``max_replicas``-style variable in the module
    API, a module-call argument of that shape, or a pod env var
    carrying the bounds to the runtime."""
    for name, v in ctx.mod.variables.items():
        if _AUTOSCALE_RE.search(name):
            return f'variable "{name}"'
    for mc in ctx.mod.module_calls.values():
        for a in mc.body.attributes:
            if _AUTOSCALE_RE.search(a.name):
                return f'module "{mc.name}" argument "{a.name}"'
    for r in ctx.mod.resources.values():
        for node in A.walk(r.body):
            if not (isinstance(node, A.Block) and node.type == "env"):
                continue
            na = node.body.attr("name")
            val = ctx.resolve_literal(na.expr) if na is not None else None
            if isinstance(val, str) and _AUTOSCALE_RE.search(val):
                return f'{r.address} env "{val}"'
    return None


@rule("tpu-serving-autoscaler-unused", severity="warning", family="tpu",
      summary="serving-shaped TPU pool declares autoscaling headroom "
              "(max above min) that no workload consumes — capacity "
              "the fixed-size serving fleet will never join")
def check_serving_autoscaler_unused(ctx: LintContext):
    """The INVERSE of ``tpu-spot-serving-no-headroom``: that rule
    fires when a serving pool has NO headroom to fail over into; this
    one fires when the headroom exists but NOTHING consumes it. A
    serving-shaped TPU pool declaring ``max_node_count`` above
    ``min_node_count`` pays for an autoscaler range — but the serving
    runtime's fleet is FIXED-size unless its elastic control loop is
    armed (``make_fleet(autoscale=AutoscalePolicy(min_replicas=…,
    max_replicas=…))``, the runtime twin of exactly these node-pool
    variables — see the "Elastic fleet runbook" in
    ``gke-tpu/README.md``). With no autoscale wiring statically
    visible in the module (an ``autoscale``/``min_replicas``-style
    variable, module argument, or pod env var), a scale-up provisions
    nodes no replica ever joins — the node autoscaler grows the bill,
    ``fleet_size`` stays flat — and a scale-down reclaims capacity the
    router was never told to drain first. Either wire the bounds into
    the serving runtime so joins are warm and drains are planned, or
    pin the pool (``max == min``) and let
    ``tpu-spot-serving-no-headroom`` arbitrate whether THAT is safe."""
    wiring = _autoscale_wiring(ctx)
    if wiring is not None:
        return
    for r in ctx.mod.resources.values():
        if r.type != "google_container_node_pool":
            continue
        shaped = _serving_shaped(ctx, r)
        if shaped is None:
            continue
        ncs = r.body.blocks_of("node_config")
        mt = _literal(ctx, ncs[0].body.attr("machine_type")) \
            if ncs else None
        is_tpu = isinstance(mt, str) \
            and T.parse_machine_type(mt) is not None
        if not is_tpu:
            is_tpu = any(
                pbody is not None
                and pbody.attr("tpu_topology") is not None
                for _blk, pbody in _placement_blocks(r.body))
        if not is_tpu:
            continue
        for b in _named_blocks(r.body, "autoscaling"):
            if b is None:
                continue
            for lo_k, hi_k in (
                    ("min_node_count", "max_node_count"),
                    ("total_min_node_count", "total_max_node_count")):
                lo = _literal(ctx, b.attr(lo_k))
                hi = _literal(ctx, b.attr(hi_k))
                if isinstance(lo, (int, float)) \
                        and isinstance(hi, (int, float)) and hi > lo:
                    yield (f"{r.file}:{r.line}",
                           f"{r.address}: serving-shaped ({shaped!r}) "
                           f"TPU pool declares {hi_k} = {hi:g} above "
                           f"{lo_k} = {lo:g} but nothing in this "
                           f"module consumes the bounds — the serving "
                           f"fleet stays fixed-size, so scaled-up "
                           f"nodes sit idle (fleet_size never moves) "
                           f"and scale-downs reclaim replicas the "
                           f"router never drained; wire the bounds "
                           f"into the runtime (make_fleet autoscale=, "
                           f"min_replicas/max_replicas mirroring "
                           f"{lo_k}/{hi_k} — the gke-tpu README's "
                           f"elastic-fleet runbook) or pin the pool "
                           f"and let tpu-spot-serving-no-headroom "
                           f"judge the pinning")


def _slice_containers(ctx: LintContext):
    """Every place a whole ``tpu_slices`` map is declared — as
    ``(fname, nap_expr, [SliceDecl, …], origin)`` — with the
    ``node_auto_provisioning`` expression that travels WITH that map:
    the sibling argument for module calls and tfvars, the module's own
    ``node_auto_provisioning`` variable default for the variable-default
    container. Reuses :func:`_decls_from_object` so ``optional()``
    default inheritance has exactly one implementation."""
    def nap_of(body):
        a = body.attr("node_auto_provisioning") if body else None
        return a.expr if a is not None else None

    for mc in ctx.mod.module_calls.values():
        a = mc.body.attr("tpu_slices")
        if a is None:
            continue
        child = ctx.child_modules().get(mc.name)
        defaults = _optional_defaults(
            child.variables.get("tpu_slices") if child else None)
        # an absent NAP argument inherits the child's own variable
        # default, exactly like the slice fields inherit optional()s
        child_nap = child.variables.get("node_auto_provisioning") \
            if child else None
        yield (mc.file,
               nap_of(mc.body) if mc.body.attr("node_auto_provisioning")
               is not None else
               (child_nap.default if child_nap is not None else None),
               _decls_from_object(ctx, mc.file, a.expr,
                                  f"module {mc.name!r} call",
                                  defaults=defaults),
               f"module {mc.name!r} call")
    own_defaults = _optional_defaults(ctx.mod.variables.get("tpu_slices"))
    own_nap = ctx.mod.variables.get("node_auto_provisioning")
    own_nap_expr = own_nap.default if own_nap is not None else None
    for fname, body in ctx.tfvars_bodies():
        a = body.attr("tpu_slices")
        if a is not None:
            yield (fname, nap_of(body) or own_nap_expr,
                   _decls_from_object(ctx, fname, a.expr, "tfvars",
                                      defaults=own_defaults),
                   "tfvars")
    v = ctx.mod.variables.get("tpu_slices")
    if v is not None and v.default is not None:
        yield (v.file, own_nap_expr,
               _decls_from_object(ctx, v.file, v.default,
                                  "variable default",
                                  defaults=own_defaults),
               "variable default")


def _nap_grants_tpu_range(ctx: LintContext, expr) -> bool:
    """True when a ``node_auto_provisioning`` expression statically
    enables NAP **with a TPU resource range** — the autoscaler posture
    that lets a reclaimed slice's capacity come back without a human
    apply. ``enabled = true`` alone is not enough: NAP only provisions
    what ``resource_limits`` allows, so without a ``tpu-…-chips`` entry
    the fleet still cannot grow back. A ``resource_limits`` that is not
    statically a list (a var reference) gets the benefit of the doubt —
    pre-flight lint must not false-positive a config it cannot see."""
    if not isinstance(expr, A.ObjectExpr):
        return False
    fields = {k: v for k, v, _ in _object_items(expr)}
    if "enabled" not in fields or \
            ctx.resolve_literal(fields["enabled"]) is not True:
        return False
    limits = fields.get("resource_limits")
    if limits is None:
        return False
    if not isinstance(limits, A.TupleExpr):
        return True   # statically opaque: assume the operator sized it
    for item in limits.items:
        if not isinstance(item, A.ObjectExpr):
            continue
        entry = {k: v for k, v, _ in _object_items(item)}
        rtype = ctx.resolve_literal(entry.get("resource_type")) \
            if "resource_type" in entry else None
        if isinstance(rtype, str) and "tpu" in rtype:
            return True
    return False


@rule("tpu-multislice-no-elastic", severity="warning", family="tpu",
      summary="spot multislice fleet with a pinned slice count and no "
              "autoscaler range or queued grow-back path")
def check_multislice_no_elastic(ctx: LintContext):
    """A multislice fleet (≥ 2 ``tpu_slices`` entries) on spot capacity
    WILL shrink — preemption reclaims whole slices, and the elastic
    runtime (``models/resilience.py``, ``TPU_ELASTIC_MIN_WORLD``) keeps
    training on the survivors — but only the *infrastructure* can grow
    the fleet back. A config that pins the slice count (a fixed
    ``tpu_slices`` map declares exactly N pools of exactly ``hosts``
    nodes each) while enabling spot, with ``node_auto_provisioning``
    disabled and no ``queued_provisioning`` slice, has no grow-back path
    at all: the world shrinks monotonically until it hits the elastic
    floor and the job dies anyway — the autoscaling the spot discount
    was supposed to buy never happens. The third leg of the spot
    tripod: ``tpu-spot-no-recovery`` is the pool's retry posture,
    ``tpu-spot-no-grace`` saves the *step*, this rule saves the
    *fleet*."""
    for fname, nap_expr, slices, origin in _slice_containers(ctx):
        if len(slices) < 2:
            continue
        spot = [s for s in slices if s.spot is True]
        if not spot:
            continue
        if any(s.queued is True for s in slices):
            continue   # DWS flex-start slices ARE a grow-back path
        if _nap_grants_tpu_range(ctx, nap_expr):
            continue
        first = spot[0]
        yield (f"{fname}:{first.line}",
               f"tpu_slices[{first.name!r}] ({origin}): {len(spot)} of "
               f"{len(slices)} slices are spot but the slice count is "
               f"pinned with no autoscaler range — a reclaimed slice "
               f"shrinks the training world and nothing grows it back "
               f"(elastic resume only keeps the survivors alive, down to "
               f"TPU_ELASTIC_MIN_WORLD); enable node_auto_provisioning "
               f"with a TPU resource_limits range, or make one slice "
               f"queued_provisioning so returned capacity rejoins the "
               f"fleet")


def _named_blocks(body, name: str):
    """``name`` blocks of a body, static or ``dynamic`` (content bodies;
    a contentless dynamic yields None like ``_placement_blocks``)."""
    out = []
    for b in body.blocks:
        if b.type == name:
            out.append(b.body)
        elif b.type == "dynamic" and b.labels and b.labels[0] == name:
            contents = b.body.blocks_of("content")
            out.extend(c.body for c in contents)
            if not contents:
                out.append(None)
    return out


def _has_tpu_capacity(ctx: LintContext) -> bool:
    """Any statically-visible TPU capacity: a slice declaration or a
    literal TPU node pool (by machine type or a tpu_topology placement)."""
    if slice_declarations(ctx):
        return True
    for r in ctx.mod.resources.values():
        if r.type != "google_container_node_pool":
            continue
        ncs = r.body.blocks_of("node_config")
        mt = _literal(ctx, ncs[0].body.attr("machine_type")) if ncs else None
        if isinstance(mt, str) and T.parse_machine_type(mt) is not None:
            return True
        if any(p is not None and p.attr("tpu_topology") is not None
               for _b, p in _placement_blocks(r.body)):
            return True
    return False


@rule("tpu-no-monitoring", severity="warning", family="tpu",
      summary="TPU cluster with cluster monitoring / managed Prometheus "
              "left disabled or declared-but-unwired")
def check_no_monitoring(ctx: LintContext):
    """A TPU fleet is exactly the capacity you cannot debug blind: spot
    slices churn (``tpu-spot-no-grace``'s premise), elastic resume
    changes the world size under the job, and the workload's own
    telemetry (the ``TPU_TELEMETRY_DIR`` Prometheus textfile, the
    runtime health-probe gauges) needs a scrape pipeline to land in.
    A ``google_container_cluster`` provisioned next to TPU node pools
    with no ``monitoring_config`` — or with
    ``managed_prometheus { enabled = false }`` — ships a fleet whose
    first preemption incident is investigated with ``kubectl logs``
    archaeology. The *declared-but-unwired* variant is the sneaky one: a
    ``monitoring``/``prometheus`` variable exists in the module's API,
    reviewers see it and assume observability is on, but no cluster
    block ever reads it."""
    if not _has_tpu_capacity(ctx):
        return
    # module-API variables that look like monitoring knobs, for the
    # declared-but-unwired diagnosis
    knobs = sorted(n for n in ctx.mod.variables
                   if "monitoring" in n or "prometheus" in n)
    for r in ctx.mod.resources.values():
        if r.type != "google_container_cluster":
            continue
        where = f"{r.file}:{r.line}"
        mcs = [b for b in _named_blocks(r.body, "monitoring_config")
               if b is not None]
        if not mcs:
            if knobs:
                yield (where,
                       f"{r.address}: provisions TPU capacity with no "
                       f"monitoring_config block, while variable(s) "
                       f"{', '.join(repr(k) for k in knobs)} are declared "
                       f"but never wired into one — reviewers will "
                       f"assume observability is on; add "
                       f"monitoring_config {{ managed_prometheus {{ "
                       f"enabled = … }} }} reading them")
            else:
                yield (where,
                       f"{r.address}: provisions TPU capacity with "
                       f"cluster monitoring left at defaults (no "
                       f"monitoring_config block) — spot churn, elastic "
                       f"resume, and the workload's Prometheus textfile "
                       f"telemetry all need managed collection; declare "
                       f"monitoring_config {{ managed_prometheus {{ "
                       f"enabled = true }} }}")
            continue
        for mc in mcs:
            for mp in _named_blocks(mc, "managed_prometheus"):
                if mp is None:
                    continue
                attr = mp.attr("enabled")
                enabled = _literal(ctx, attr)
                # unresolvable (a var reference) gets the benefit of the
                # doubt — pre-flight lint must not false-positive what
                # it cannot see
                if enabled is False:
                    line = attr.line if attr is not None and attr.line \
                        else r.line
                    yield (f"{r.file}:{line}",
                           f"{r.address}: managed_prometheus is "
                           f"explicitly disabled on a TPU cluster — the "
                           f"fleet's step-latency/MFU/SLO metrics have "
                           f"nowhere to land; enable it or wire an "
                           f"external scrape")


@rule("tpu-multihost-placement", severity="error", family="tpu",
      summary="multi-host TPU pool without a COMPACT placement policy")
def check_multihost_placement(ctx: LintContext):
    """A multi-host slice is one ICI mesh: without
    ``placement_policy { type = "COMPACT" tpu_topology = … }`` GKE
    scatters the hosts and the slice never assembles.

    A non-COMPACT placement type on a TPU pool is a definitive error.
    ``node_count > 1`` with NO placement policy is only a *warning*: the
    pool may legitimately be N independent single-host slices — and on
    machines that exist only via single-host packing (1t/8t, see
    :data:`tpu_facts.SINGLE_HOST_PACK`) that is the ONLY reading, so
    those are skipped entirely (a pre-flight check must never
    false-positive a valid fleet into a blocked apply)."""
    for r in ctx.mod.resources.values():
        if r.type != "google_container_node_pool":
            continue
        ncs = r.body.blocks_of("node_config")
        if not ncs:
            continue
        mt = _literal(ctx, ncs[0].body.attr("machine_type"))
        if not isinstance(mt, str):
            continue
        parsed = T.parse_machine_type(mt)
        if parsed is None:
            continue
        gen, host_chips = parsed
        where = f"{r.file}:{r.line}"
        placements = _placement_blocks(r.body)
        for blk, pbody in placements:
            if pbody is None:
                continue
            ptype = _literal(ctx, pbody.attr("type"))
            if isinstance(ptype, str) and ptype != "COMPACT":
                yield (f"{r.file}:{blk.line}",
                       f"{r.address}: TPU placement_policy type is "
                       f"{ptype!r} — multi-host TPU slices require "
                       f"\"COMPACT\" (one ICI mesh)")
        if placements:
            continue
        if host_chips != T.CHIPS_PER_HOST[gen]:
            # 1t/8t machines exist only via single-host packing: each
            # node is its own whole slice, any node_count is valid
            continue
        node_count = _literal(ctx, r.body.attr("node_count"))
        if isinstance(node_count, int) and node_count > 1:
            yield Finding(
                "warning", where,
                f"{r.address}: {node_count} hosts of TPU machine "
                f"{mt!r} with no placement_policy — if this pool is one "
                f"multi-host slice it needs placement_policy {{ type = "
                f"\"COMPACT\" tpu_topology = … }} or the hosts never "
                f"form one ICI mesh (independent single-host slices can "
                f"ignore this)")
